#!/usr/bin/env python
"""box_game SyncTest CLI — port of
/root/reference/examples/box_game/box_game_synctest.rs: continuous
check-distance resimulation with panic-on-mismatch."""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

from bevy_ggrs_tpu import GgrsRunner, SessionBuilder
from bevy_ggrs_tpu.models import box_game


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-players", type=int, default=2)
    ap.add_argument("--check-distance", type=int, default=7)
    ap.add_argument("--input-delay", type=int, default=0)
    ap.add_argument("--frames", type=int, default=600)
    args = ap.parse_args()

    app = box_game.make_app(num_players=args.num_players)
    session = (
        SessionBuilder.for_app(app)
        .with_num_players(args.num_players)
        .with_check_distance(args.check_distance)
        .with_input_delay(args.input_delay)
        .start_synctest_session()
    )

    def on_mismatch(e):
        raise SystemExit(f"SYNCTEST MISMATCH: {e}")  # panic observer

    def read_inputs(handles):
        phase = (runner.frame // 30) % 4
        kw = [dict(right=True), dict(up=True), dict(left=True), dict(down=True)][phase]
        return {h: box_game.keys_to_input(**kw) for h in handles}

    runner = GgrsRunner(app, session, read_inputs=read_inputs, on_mismatch=on_mismatch)
    t0 = time.perf_counter()
    for _ in range(args.frames):
        runner.tick()
    dt = time.perf_counter() - t0
    print(f"{args.frames} frames (x{args.check_distance + 1} resim each) in "
          f"{dt:.2f}s — no mismatches; pos0={runner.world.comps['pos'][0].tolist()}")


if __name__ == "__main__":
    main()
