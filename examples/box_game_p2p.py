#!/usr/bin/env python
"""box_game P2P CLI — headless port of the reference example
(/root/reference/examples/box_game/box_game_p2p.rs): 2-4 players over UDP,
desync detection interval 10, max_prediction 12, input_delay 2, event and
network-stats printers.

Run two processes:
    python examples/box_game_p2p.py --local-port 8081 --players local 127.0.0.1:8082
    python examples/box_game_p2p.py --local-port 8082 --players 127.0.0.1:8081 local
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

from bevy_ggrs_tpu import (
    DesyncDetection,
    GgrsRunner,
    PlayerType,
    SessionBuilder,
    UdpNonBlockingSocket,
)
from bevy_ggrs_tpu.models import box_game


def parse_addr(s):
    host, port = s.rsplit(":", 1)
    return (host, int(port))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--local-port", type=int, required=True)
    ap.add_argument("--players", nargs="+", required=True,
                    help="'local' or host:port per handle")
    ap.add_argument("--spectators", nargs="*", default=[])
    ap.add_argument("--input-delay", type=int, default=2)
    ap.add_argument("--max-prediction", type=int, default=12)
    ap.add_argument("--fps", type=int, default=60)
    ap.add_argument("--frames", type=int, default=600)
    ap.add_argument("--canonical", action="store_true",
                    help="bit-determinism program (docs/determinism.md): "
                         "required when peers' float rounding must match "
                         "exactly; costs max_prediction+2 frames of compute "
                         "per dispatch (cheap on TPU, heavy on CPU)")
    ap.add_argument("--tcp", action="store_true",
                    help="framed-TCP transport instead of UDP (for networks "
                         "that block UDP; all peers must agree)")
    args = ap.parse_args()

    app = box_game.make_app(
        num_players=len(args.players), fps=args.fps,
        canonical_depth=(args.max_prediction + 2) if args.canonical else None,
    )
    if args.tcp:
        from bevy_ggrs_tpu import TcpNonBlockingSocket

        sock = TcpNonBlockingSocket(args.local_port)
    else:
        sock = UdpNonBlockingSocket(args.local_port)
    b = (
        SessionBuilder.for_app(app)
        .with_num_players(len(args.players))
        .with_input_delay(args.input_delay)
        .with_max_prediction_window(args.max_prediction)
        .with_desync_detection_mode(DesyncDetection.on(10))
    )
    local_handle = None
    for handle, spec in enumerate(args.players):
        if spec == "local":
            b.add_player(PlayerType.LOCAL, handle)
            local_handle = handle
        else:
            b.add_player(PlayerType.REMOTE, handle, parse_addr(spec))
    for i, spec in enumerate(args.spectators):
        b.add_player(PlayerType.SPECTATOR, len(args.players) + i, parse_addr(spec))
    session = b.start_p2p_session(sock)

    def read_inputs(handles):
        # demo input: local player circles (right for 60 frames, up for 60, ...)
        phase = (runner.frame // 60) % 4
        kw = [dict(right=True), dict(up=True), dict(left=True), dict(down=True)][phase]
        return {h: box_game.keys_to_input(**kw) for h in handles}

    runner = GgrsRunner(app, session, read_inputs=read_inputs,
                        on_event=lambda e: print(f"event: {e}"))
    last = time.perf_counter()
    last_print = 0.0
    while runner.frame < args.frames:
        now = time.perf_counter()
        runner.update(now - last)
        last = now
        if now - last_print > 1.0:
            last_print = now
            pos = runner.world.comps["pos"]
            print(f"frame {runner.frame} confirmed {runner.confirmed} "
                  f"pos0={pos[0].tolist()}")
            for h in range(len(args.players)):
                if h != local_handle:
                    try:
                        s = session.network_stats(h)
                        print(f"  stats p{h}: ping={s.ping_ms:.1f}ms "
                              f"kbps={s.kbps_sent:.1f} queue={s.send_queue_len}")
                    except Exception:
                        pass
        time.sleep(0.001)
    print(f"done at frame {runner.frame}")


if __name__ == "__main__":
    main()
