#!/usr/bin/env python
"""box_game over room matchmaking — the matchbox-style flow
(/root/reference/README.md:79: matchbox pairs with the reference for
browser P2P; here the same join-room → learn-peers → play contract runs
over UDP via bevy_ggrs_tpu.session.room).

Start a server, then two players (any machines that can reach it):

    python scripts/room_server.py --port 3536
    python examples/box_game_room.py --server 127.0.0.1:3536 --room demo
    python examples/box_game_room.py --server 127.0.0.1:3536 --room demo

Handles come from the sorted-peer-id convention (the first --players ids
seat the game), so both processes derive the same assignment with no flags.  --relay forces the
TURN-style data plane through the server.
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

from bevy_ggrs_tpu import (
    GgrsRunner,
    PlayerType,
    RoomSocket,
    SessionBuilder,
    SessionState,
    wait_for_players,
)
from bevy_ggrs_tpu.models import box_game


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--server", default="127.0.0.1:3536")
    ap.add_argument("--room", default="demo")
    ap.add_argument("--players", type=int, default=2)
    ap.add_argument("--frames", type=int, default=600)
    ap.add_argument("--relay", action="store_true",
                    help="force the relayed data plane")
    ap.add_argument("--peer-id", default=None)
    args = ap.parse_args()

    ip, port = args.server.rsplit(":", 1)
    sock = RoomSocket(
        (ip, int(port)), args.room, peer_id=args.peer_id,
        mode="relay" if args.relay else "direct",
    )
    print(f"joined room '{args.room}' as {sock.peer_id}; waiting for "
          f"{args.players} players...", flush=True)
    wait_for_players(sock, args.players, timeout_s=60.0)
    # the game seats exactly --players: the FIRST n sorted peer ids play
    # (deterministic on every peer); later arrivals are spectator-less
    # bystanders and must bail out rather than derive an out-of-range handle
    players = sock.players()[: args.players]
    handles = dict(enumerate(players))
    if sock.peer_id not in players:
        print(f"room already seated {args.players} players "
              f"({players}); {sock.peer_id} is not among them — exiting",
              flush=True)
        sock.close()
        sys.exit(1)
    print(f"room full: {players}; handles: {handles}", flush=True)

    app = box_game.make_app(num_players=args.players)
    b = SessionBuilder.for_app(app).with_input_delay(2)
    my_handle = None
    for h, peer in handles.items():
        if peer == sock.peer_id:
            b.add_player(PlayerType.LOCAL, h)
            my_handle = h
        else:
            b.add_player(PlayerType.REMOTE, h, peer)
    session = b.start_p2p_session(sock)

    key = ["right", "down", "left", "up"][my_handle % 4]

    def read_inputs(hs):
        return {h: box_game.keys_to_input(**{key: True}) for h in hs}

    runner = GgrsRunner(app, session, read_inputs=read_inputs,
                        on_event=lambda e: print(f"event: {e}", flush=True))

    last = time.monotonic()
    while session.current_state() != SessionState.RUNNING:
        runner.update(0.0)
        time.sleep(0.002)
    print("synchronized; playing", flush=True)
    while runner.frame < args.frames:
        now = time.monotonic()
        runner.update(now - last)
        last = now
        time.sleep(0.001)
    print(f"done at frame {runner.frame}; checksum {runner.checksum:#018x}",
          flush=True)
    sock.close()


if __name__ == "__main__":
    main()
