#!/usr/bin/env python
"""box_game spectator CLI — port of
/root/reference/examples/box_game/box_game_spectator.rs: follow a host
session read-only.

    python examples/box_game_spectator.py --local-port 8090 \
        --host 127.0.0.1:8081 --num-players 2
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

from bevy_ggrs_tpu import GgrsRunner, SessionBuilder, UdpNonBlockingSocket
from bevy_ggrs_tpu.models import box_game


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--local-port", type=int, required=True)
    ap.add_argument("--host", required=True)
    ap.add_argument("--num-players", type=int, default=2)
    ap.add_argument("--frames", type=int, default=600)
    args = ap.parse_args()

    host, port = args.host.rsplit(":", 1)
    app = box_game.make_app(num_players=args.num_players)
    sock = UdpNonBlockingSocket(args.local_port)
    session = (
        SessionBuilder.for_app(app)
        .with_num_players(args.num_players)
        .start_spectator_session((host, int(port)), sock)
    )
    runner = GgrsRunner(app, session, on_event=lambda e: print(f"event: {e}"))
    last = time.perf_counter()
    last_print = 0.0
    while runner.frame < args.frames:
        now = time.perf_counter()
        runner.update(now - last)
        last = now
        if now - last_print > 1.0:
            last_print = now
            print(f"frame {runner.frame} (behind host: "
                  f"{session.frames_behind_host()}) "
                  f"pos0={runner.world.comps['pos'][0].tolist()}")
        time.sleep(0.001)


if __name__ == "__main__":
    main()
