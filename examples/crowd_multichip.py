#!/usr/bin/env python
"""Multi-chip crowd demo: a large flocking sim sharded over a device mesh,
with speculative branches on the "spec" axis — the scale-out path
(docs/architecture.md "Multi-chip").

    BGT_PLATFORM=cpu BGT_CPU_DEVICES=8 python examples/crowd_multichip.py
    # on a TPU pod slice: just run it (uses all visible devices)
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from bevy_ggrs_tpu.utils.platform import apply_platform_env

apply_platform_env()

import jax
import numpy as np

from bevy_ggrs_tpu.models import crowd
from bevy_ggrs_tpu.parallel import make_mesh, make_sharded_resim_fn, make_sharded_speculate_fn
from bevy_ggrs_tpu.snapshot.checksum import checksum_to_int


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--per-team", type=int, default=4096)
    ap.add_argument("--teams", type=int, default=2)
    ap.add_argument("--frames", type=int, default=64)
    ap.add_argument("--branches", type=int, default=4)
    ap.add_argument("--spec-axis", type=int, default=2)
    args = ap.parse_args()

    n_dev = len(jax.devices())
    n_spec = args.spec_axis if n_dev % args.spec_axis == 0 else 1
    mesh = make_mesh(n_data=n_dev // n_spec, n_spec=n_spec)
    print(f"devices: {n_dev} ({jax.devices()[0].platform}), mesh {dict(mesh.shape)}")

    app = crowd.make_app(n_per_team=args.per_team, num_teams=args.teams)
    world = app.init_state()
    k = 8
    inputs = np.zeros((k, args.teams), np.uint8)
    status = np.zeros((k, args.teams), np.int8)

    resim = make_sharded_resim_fn(app, mesh)
    out = resim(world, inputs, status, 0)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    steps = max(args.frames // k, 1)
    w = world
    for i in range(steps):
        w, stacked, checks = resim(w, inputs, status, i * k)
    jax.block_until_ready(w)
    dt = time.perf_counter() - t0
    n = args.per_team * args.teams
    print(f"sharded resim: {steps * k} frames x {n} boids in {dt:.2f}s "
          f"({steps * k / dt:.0f} fps), checksum {checksum_to_int(checks[-1]):#x}")

    spec = make_sharded_speculate_fn(app, mesh)
    bi = np.zeros((args.branches, k, args.teams), np.uint8)
    for b in range(args.branches):
        bi[b, :, :] = b  # distinct steering per branch
    bs = np.zeros((args.branches, k, args.teams), np.int8)
    out = spec(world, bi, bs, 0)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    finals, stacked, checks = spec(world, bi, bs, 0)
    jax.block_until_ready(checks)
    dt = time.perf_counter() - t0
    print(f"speculative fan-out: {args.branches} branches x {k} frames in "
          f"{dt * 1e3:.0f} ms ({args.branches * k / dt:.0f} resim-fps)")


if __name__ == "__main__":
    main()
