"""Speculative rollback cache — driver-side branch fan-out.

The capability beyond the reference (SURVEY §2.4 "Speculation"): while the
session advances on *predicted* remote inputs, the driver simultaneously
evaluates M candidate input branches for the same transition in ONE
``jit(vmap(scan))`` dispatch.  When the real input arrives and the session
requests a rollback, the first resimulated frame is looked up in the cache:
a depth-1 rollback (the common case under mild jitter) becomes a branch
select with zero extra device work; deeper rollbacks skip their first
frame's recompute.

Usage: pass ``SpeculationConfig`` to :class:`~bevy_ggrs_tpu.runner.GgrsRunner`.
``candidates_fn(last_inputs) -> [M, P, *input_shape]`` enumerates the input
combinations to hedge against (e.g. all 16 values of a 4-bit pad for the
remote player, local inputs held fixed).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

import numpy as np


@dataclass
class SpeculationConfig:
    """candidates_fn: maps the inputs just used (``[P, *shape]``) to an
    ``[M, P, *shape]`` array of candidate input rows for the SAME frame.
    Should include likely corrections of the predicted players' inputs."""

    candidates_fn: Callable[[np.ndarray], np.ndarray]
    max_cached_frames: int = 4  # keep branches for the newest N start frames


class SpeculationCache:
    def __init__(self, app, config: SpeculationConfig):
        self.app = app
        self.config = config
        # start_frame -> { input_bytes : (state, checksum) }
        self._cache: Dict[int, Dict[bytes, Tuple]] = {}
        self.hits = 0
        self.misses = 0
        self.branches_evaluated = 0

    def speculate(self, world, start_frame: int, used_inputs: np.ndarray) -> None:
        """Fan out candidate branches for the (start_frame -> start_frame+1)
        transition from ``world`` (the pre-advance state)."""
        cands = np.asarray(
            self.config.candidates_fn(used_inputs), self.app.input_dtype
        )
        m = cands.shape[0]
        if m == 0:
            return
        branches = cands[:, None]  # [M, k=1, P, *shape]
        statuses = np.zeros((m, 1, self.app.num_players), np.int8)
        finals, stacked, checks = self.app.speculate_fn(
            world, branches, statuses, start_frame
        )
        self.branches_evaluated += m
        from .resim import select_branch

        entry = {}
        for b in range(m):
            key = np.ascontiguousarray(cands[b]).tobytes()
            entry[key] = (select_branch(finals, b), checks[b, 0])
        self._cache[start_frame] = entry
        # trim old start frames
        for f in sorted(self._cache):
            if len(self._cache) <= self.config.max_cached_frames:
                break
            del self._cache[f]

    def lookup(self, start_frame: int, inputs: np.ndarray) -> Optional[Tuple]:
        """(state, checksum) for advancing ``start_frame`` with ``inputs``,
        if that branch was speculated."""
        entry = self._cache.get(start_frame)
        if entry is None:
            self.misses += 1
            return None
        key = np.ascontiguousarray(
            np.asarray(inputs, self.app.input_dtype)
        ).tobytes()
        got = entry.get(key)
        if got is None:
            self.misses += 1
        else:
            self.hits += 1
        return got

    def clear(self) -> None:
        self._cache.clear()


def pad_candidates(num_players: int, predicted_handles, values) -> Callable:
    """Convenience candidates_fn: enumerate ``values`` for every predicted
    handle (cartesian over handles), holding other players' inputs as used."""
    import itertools

    def fn(used_inputs: np.ndarray) -> np.ndarray:
        combos = list(itertools.product(values, repeat=len(predicted_handles)))
        out = np.repeat(used_inputs[None], len(combos), axis=0).copy()
        for i, combo in enumerate(combos):
            for h, v in zip(predicted_handles, combo):
                out[i, h] = v
        return out

    return fn
