"""Speculative rollback cache — driver-side branch fan-out.

The capability beyond the reference (SURVEY §2.4 "Speculation"): while the
session advances on *predicted* remote inputs, the driver simultaneously
evaluates M candidate input branches for the same transition in ONE
``jit(vmap(scan))`` dispatch.  When the real input arrives and the session
requests a rollback, the first resimulated frame is looked up in the cache:
a depth-1 rollback (the common case under mild jitter) becomes a branch
select with zero extra device work; deeper rollbacks skip their first
frame's recompute.

Usage: pass ``SpeculationConfig`` to :class:`~bevy_ggrs_tpu.runner.GgrsRunner`.
``candidates_fn(last_inputs) -> [M, P, *input_shape]`` enumerates the input
combinations to hedge against (e.g. all 16 values of a 4-bit pad for the
remote player, local inputs held fixed).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import numpy as np


@dataclass
class SpeculationConfig:
    """candidates_fn: maps the inputs just used (``[P, *shape]``) to an
    ``[M, P, *shape]`` array of candidate input rows.  Should include likely
    corrections of the predicted players' inputs.

    ``depth``: each branch extends its candidate row ``depth`` frames forward
    (repeat-last continuation — matching how PredictRepeatLast mispredicts:
    the remote *held* an input we did not guess).  A rollback of d <= depth
    frames whose corrected inputs are constant and hedged becomes a cache
    select of the d-th stacked state; depth=1 recovers single-frame hedging.
    The speculate dispatch costs M x depth frames of device work per
    predicted tick (the north-star 16 branches x 8 frames shape)."""

    candidates_fn: Callable[[np.ndarray], np.ndarray]
    depth: int = 1
    max_cached_frames: int = 4  # keep branches for the newest N start frames
    # Memory note: the cache retains M x depth x max_cached_frames world
    # snapshots on device (they share nothing with the ring).  For a 10k-
    # entity world that is a few hundred KB per snapshot; for very large
    # worlds lower depth/max_cached_frames or hedge fewer candidates —
    # or set ``max_cached_bytes`` to let the cache bound itself.
    #: Device-byte budget across all cached start frames (None = unbounded
    #: beyond ``max_cached_frames``).  Oldest start frames evict first; the
    #: NEWEST entry is always retained even if it alone exceeds the budget
    #: (an empty cache would silently disable speculation), so the hard
    #: ceiling is max(max_cached_bytes, one entry's footprint).
    max_cached_bytes: Optional[int] = None


class SpeculationCache:
    """Branch cache: speculated (start_frame, inputs) -> per-frame states + checksums."""
    def __init__(self, app, config: SpeculationConfig):
        self.app = app
        self.config = config
        # start_frame -> { input_bytes : (state, checksum) }
        self._cache: Dict[int, Dict[bytes, Tuple]] = {}
        self._entry_bytes: Dict[int, int] = {}  # start_frame -> device bytes
        self.hits = 0
        self.misses = 0
        self.branches_evaluated = 0
        self.bytes_evicted = 0  # device bytes dropped by the BYTE budget only
        self.draft_dispatches = 0  # speculative fan-out dispatches issued
        # Packed single-upload staging for the speculate dispatch (same
        # scheme as the runner's resim path — ops/packing.py): persistent
        # [M, depth+1, W] int8 buffer, grown geometrically if M changes.
        self._packed_buf: Optional[np.ndarray] = None
        self.host_uploads = 0
        self.packed_upload_bytes = 0
        from .. import telemetry

        _treg = telemetry.registry()
        self._m_uploads = _treg.bind_histogram(
            "uploads_per_dispatch",
            "host->device uploads issued per fused dispatch (1 on the "
            "packed path)",
            buckets=(1, 2, 3, 4, 8),
        )
        self._m_packed_bytes = _treg.bind_counter(
            "packed_upload_bytes",
            "bytes staged through packed single-upload buffers",
        )
        self._m_drafts = _treg.bind_counter(
            "draft_dispatches_total",
            "speculative draft dispatches issued into idle pipeline slots "
            "/ spare wave lanes",
        )
        # device-memory accounting (telemetry/devmem.py): the branch cache
        # pins whole speculated worlds — exactly the residency the HBM
        # budget (max_cached_bytes) exists to bound
        import weakref

        from ..telemetry import devmem

        self._devmem_owner = devmem.scope("speculation") + "/branch_cache"
        weakref.finalize(self, devmem.forget, self._devmem_owner)

    @property
    def cached_bytes(self) -> int:
        """Device bytes currently pinned by cached branch states."""
        return sum(self._entry_bytes.values())

    def _renote(self) -> None:
        from ..telemetry import devmem

        devmem.note(self._devmem_owner, self.cached_bytes)

    def _account(self, start_frame: int, entry: Dict) -> None:
        from ..utils.mem import tree_device_bytes

        self._entry_bytes[start_frame] = sum(
            tree_device_bytes(branch) for branch in entry.values()
        )
        self._renote()

    def _stage_packed(self, cands: np.ndarray, start_frame: int,
                      depth: int) -> np.ndarray:
        """Stage the M candidate branches into the persistent packed buffer
        (one row per frame: the candidate held constant, statuses zero —
        the exact bytes the unpacked path uploads as three arrays)."""
        from .packing import pack_prefix, pack_row, repeat_last_row

        spec = self.app.packed_spec
        m = cands.shape[0]
        buf = self._packed_buf
        if buf is None or buf.shape[0] < m or buf.shape[1] != depth + 1:
            buf = self._packed_buf = spec.new_batch_buffer(m, depth)
        pk = buf[:m]
        zero_status = np.zeros(self.app.num_players, np.int8)
        for b in range(m):
            pack_prefix(pk[b], start_frame, depth)
            pack_row(spec, pk[b], 0, cands[b], zero_status)
            repeat_last_row(pk[b], 1, depth)
        # reused buffer + async upload: commit synchronously (utils/staging)
        from ..utils.staging import commit

        return commit(pk)

    def speculate(self, world, start_frame: int, used_inputs: np.ndarray) -> None:
        """Fan out candidate branches from ``world`` (the pre-advance state):
        each candidate input row held constant for ``config.depth`` frames."""
        cands = np.asarray(
            self.config.candidates_fn(used_inputs), self.app.input_dtype
        )
        m = cands.shape[0]
        if m == 0:
            return
        depth = max(self.config.depth, 1)
        if self.app.packed_speculate_fn is not None:
            pk = self._stage_packed(cands, start_frame, depth)
            finals, stacked, checks = self.app.packed_speculate_fn(world, pk)
            self.host_uploads += 1
            self._m_uploads.observe(1)
            self.packed_upload_bytes += pk.nbytes
            self._m_packed_bytes.inc(pk.nbytes)
        else:
            # [M, depth, P, *shape]: candidate row repeated on the frame axis
            branches = np.repeat(cands[:, None], depth, axis=1)
            statuses = np.zeros((m, depth, self.app.num_players), np.int8)
            finals, stacked, checks = self.app.speculate_fn(
                world, branches, statuses, start_frame
            )
            self.host_uploads += 3
            self._m_uploads.observe(3)
        self.draft_dispatches += 1
        self._m_drafts.inc()
        self.branches_evaluated += m * depth
        entry = {}
        for b in range(m):
            key = np.ascontiguousarray(cands[b]).tobytes()
            # per-branch stacked states [depth, ...] + checksums [depth, 2]
            entry[key] = (
                jax_tree_slice(stacked, b),
                checks[b],
            )
        self._cache[start_frame] = (depth, entry)
        self._account(start_frame, entry)
        self._trim()

    def fill_from_branched(self, start_frame: int, cands: np.ndarray,
                           stacked_b, checks_b, offset: int, depth_eff: int) -> None:
        """Store hedge-lane outputs of a canonical-branched dispatch.

        ``stacked_b``/``checks_b`` carry a leading branch axis ALIGNED with
        ``cands`` (hedge lanes only); each lane's frames [offset:] hold the
        candidate-driven continuation."""
        if depth_eff <= 0 or cands.shape[0] == 0:
            return
        entry = {}
        for b in range(cands.shape[0]):
            key = np.ascontiguousarray(cands[b]).tobytes()
            if key in entry:
                continue  # duplicate candidate (padding lanes)
            stacked_slice = jax_tree_slice_range(stacked_b, b, offset, depth_eff)
            entry[key] = (stacked_slice, checks_b[b, offset:offset + depth_eff])
        self.branches_evaluated += cands.shape[0] * depth_eff
        self._cache[start_frame] = (depth_eff, entry)
        self._account(start_frame, entry)
        self._trim()

    def lookup_seq(self, start_frame: int, inputs_seq: np.ndarray) -> Optional[Tuple]:
        """Longest cached prefix for advancing ``start_frame`` with the frame
        sequence ``inputs_seq [k, P, *shape]``.

        Returns (d, states_fn, checks) where d is the number of frames served:
        ``states_fn(i)`` yields the state after advance i (0-based, i < d) and
        ``checks[i]`` its checksum — or None on miss.  Matches only constant
        input prefixes (branches hold their candidate)."""
        got = self._cache.get(start_frame)
        if got is None:
            self.misses += 1
            return None
        depth, entry = got
        seq = np.asarray(inputs_seq, self.app.input_dtype)
        key = np.ascontiguousarray(seq[0]).tobytes()
        branch = entry.get(key)
        if branch is None:
            self.misses += 1
            return None
        d = 1
        while d < min(depth, seq.shape[0]) and np.array_equal(seq[d], seq[0]):
            d += 1
        stacked_b, checks_b = branch
        self.hits += 1
        from .resim import slice_frame

        def states_fn(i):
            return slice_frame(stacked_b, i)

        # the raw [depth, ...] branch stack, for callers that want deferred
        # LazySlice handles instead of eager per-frame selects (the batched
        # runner's ring pushes)
        states_fn.stacked = stacked_b
        return d, states_fn, checks_b

    def lookup(self, start_frame: int, inputs: np.ndarray) -> Optional[Tuple]:
        """Single-frame convenience: (state, checksum) or None."""
        got = self.lookup_seq(start_frame, np.asarray(inputs)[None])
        if got is None:
            return None
        d, states_fn, checks = got
        return states_fn(0), checks[0]

    def _oldest(self) -> int:
        from ..utils.frames import frame_lt

        oldest = next(iter(self._cache))
        for f in self._cache:
            if frame_lt(f, oldest):
                oldest = f
        return oldest

    def _drop(self, frame: int) -> int:
        del self._cache[frame]
        freed = self._entry_bytes.pop(frame, 0)
        self._renote()
        return freed

    def _trim(self) -> None:
        """Evict the OLDEST start frames past the frame cap and the device-
        byte budget, under wrapping frame order (a plain ``sorted()`` would
        evict the newest at the i32 wrap).  The newest entry always stays —
        see ``SpeculationConfig.max_cached_bytes``."""
        while len(self._cache) > self.config.max_cached_frames:
            self._drop(self._oldest())
        budget = self.config.max_cached_bytes
        if budget is not None:
            while len(self._cache) > 1 and self.cached_bytes > budget:
                self.bytes_evicted += self._drop(self._oldest())

    def invalidate_after(self, frame: int) -> None:
        """Drop entries whose base state a rollback to ``frame`` invalidates.

        An entry for start_frame s was speculated from the live state at s.
        A rollback that loads frame f re-simulates every frame after f with
        corrected inputs, so entries with s > f sit on superseded bases —
        their *inputs* can still match a later lookup (the candidate row is
        the same), which would serve bit-stale states and desync the
        speculating peer from a plain one.  The entry at s == f stays valid:
        its base is exactly the ring snapshot the load restores."""
        from ..utils.frames import frame_gt

        for s in [s for s in self._cache if frame_gt(s, frame)]:
            del self._cache[s]
            self._entry_bytes.pop(s, None)
        self._renote()

    def clear(self) -> None:
        """Drop every cached branch (and its byte accounting)."""
        self._cache.clear()
        self._entry_bytes.clear()
        self._renote()

    def drain_drafts(self) -> None:
        """Retire every in-flight draft dispatch (measurement aid).

        The runner's ``measure_rollback_service`` mode calls this at the
        speculation flush seam so draft compute is charged to the idle slot
        that issued it — without the barrier, a later rollback's servicing
        span would transitively wait on the draft program (the device
        serializes) and the ``path=hit`` histogram would time drafts."""
        import jax

        for _depth, entry in self._cache.values():
            for stacked_b, checks_b in entry.values():
                # bgt: ignore[BGT011]: deliberate — measurement mode only
                # (GgrsRunner._flush_speculation under
                # measure_rollback_service); never on the steady tick path
                jax.block_until_ready(stacked_b)


def jax_tree_slice(tree, idx):
    """tree_map(a[idx]) over a stacked pytree."""
    import jax

    return jax.tree.map(lambda a: a[idx], tree)


def jax_tree_slice_range(tree, idx, start, length):
    """tree_map(a[idx, start:start+length]) over a branch-stacked pytree."""
    import jax

    return jax.tree.map(lambda a: a[idx, start:start + length], tree)


def pad_candidates(num_players: int, predicted_handles, values) -> Callable:
    """Convenience candidates_fn: enumerate ``values`` for every predicted
    handle (cartesian over handles), holding other players' inputs as used."""
    import itertools

    def fn(used_inputs: np.ndarray) -> np.ndarray:
        combos = list(itertools.product(values, repeat=len(predicted_handles)))
        out = np.repeat(used_inputs[None], len(combos), axis=0).copy()
        for i, combo in enumerate(combos):
            for h, v in zip(predicted_handles, combo):
                out[i, h] = v
        return out

    return fn
