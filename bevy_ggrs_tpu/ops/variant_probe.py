"""Program-variant stability probe — does this model need canonical mode?

XLA compiles a different executable per resim length; executables may round
the same step differently (FMA contraction / fusion — docs/determinism.md
"One program to advance them all").  This probe measures it for a concrete
App: it drives the model's own step through the k=1 and k=K programs over
randomized reachable-ish states and inputs and bit-compares the results.

Any mismatch means peers with different rollback histories WILL drift —
configure ``App(canonical_depth=...)`` (and ``canonical_branches`` if
hedging).  Zero mismatches is strong evidence of stability for the sampled
distribution, not a proof; integer/fixed-point models are stable by
construction.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import numpy as np


@dataclass
class VariantProbeReport:
    """Result of :func:`probe_program_variants`."""

    trials: int
    mismatching_trials: int
    first_example: Optional[dict]  # {"leaf", "a", "b"} for the report
    checked_lengths: tuple

    @property
    def stable(self) -> bool:
        return self.mismatching_trials == 0

    def summary(self) -> str:
        """One-line human-readable verdict."""
        if self.stable:
            return (
                f"stable: {self.trials} random trials bit-identical across "
                f"scan lengths {self.checked_lengths} (no canonical_depth "
                "needed for the sampled distribution)"
            )
        return (
            f"UNSTABLE: {self.mismatching_trials}/{self.trials} trials "
            f"differ across scan lengths {self.checked_lengths} — configure "
            "App(canonical_depth=...) or peers will desync "
            "(docs/determinism.md)"
        )


def probe_program_variants(
    app,
    trials: int = 200,
    k_long: int = 8,
    seed: int = 0,
    warmup_frames: int = 16,
) -> VariantProbeReport:
    """Bit-compare the k=1 vs k=``k_long`` compiled programs on ``app``.

    Each trial starts from a state reached by simulating ``warmup_frames``
    random frames from init (so masks/spawns are realistic), then applies one
    random input frame through both programs and compares every state leaf.
    """
    rng = np.random.default_rng(seed)
    P = app.num_players
    ishape = (P, *app.input_shape)

    def rand_inputs(k):
        info = np.iinfo(app.input_dtype) if np.issubdtype(
            app.input_dtype, np.integer
        ) else None
        if info is not None:
            lo, hi = max(info.min, -(2**15)), min(info.max, 2**15 - 1)
            return rng.integers(lo, hi + 1, (k, *ishape)).astype(app.input_dtype)
        return rng.standard_normal((k, *ishape)).astype(app.input_dtype)

    status1 = np.zeros((1, P), np.int8)
    mismatches = 0
    first = None
    base = app.init_state()
    for t in range(trials):
        # reach a plausible state
        wk = rand_inputs(warmup_frames)
        ws = np.zeros((warmup_frames, P), np.int8)
        state, _, _ = app.resim_fn(base, wk, ws, 0)
        inp = rand_inputs(1)
        # k=1 program
        one, _, _ = app.resim_fn(state, inp, status1, warmup_frames)
        # k=k_long program, same first input then inert repeats of it; only
        # the FIRST frame's output is compared
        inp_long = np.repeat(inp, k_long, axis=0)
        stat_long = np.zeros((k_long, P), np.int8)
        _, stacked, _ = app.resim_fn(state, inp_long, stat_long, warmup_frames)
        long_first = jax.tree.map(lambda a: a[0], stacked)
        la, _ = jax.tree_util.tree_flatten_with_path(one)
        lb, _ = jax.tree_util.tree_flatten_with_path(long_first)
        for (pa, a), (_, b) in zip(la, lb):
            a = np.asarray(a)
            b = np.asarray(b)
            if not np.array_equal(a, b):
                mismatches += 1
                if first is None:
                    idx = np.argwhere(a != b)  # bgt: ignore[BGT071]: host-side numpy diagnostic on already-materialized arrays, never traced
                    first = {
                        "leaf": jax.tree_util.keystr(pa),
                        "a": a[tuple(idx[0])].item() if idx.size else None,
                        "b": b[tuple(idx[0])].item() if idx.size else None,
                    }
                break
    return VariantProbeReport(
        trials=trials,
        mismatching_trials=mismatches,
        first_example=first,
        checked_lengths=(1, k_long),
    )
