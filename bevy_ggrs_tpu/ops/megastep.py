"""Device-resident N-tick megastep — one dispatch + one upload per flush.

The coalescing path (runner.py ``coalesce_frames``) already fuses N owed
frames into one ``lax.scan`` dispatch; what still rode the link every flush
was the rollback path: a LoadRequest materialized a ring snapshot host-side
(one gather dispatch) before the advance dispatch could run.  The megastep
program moves the snapshot ring ONTO the device and folds the load into the
same dispatch, the way Octax / the Podracer "Anakin" pattern keep the whole
env loop on device (PAPERS.md):

- the device ring is a ``[R, ...]`` stacked pytree of the last R advanced
  states plus an int32 ``ring_frames[R]`` tag vector, threaded through every
  dispatch (donated, so XLA updates it in place — no per-tick ring copy);
- the packed prefix (ops/packing.py) carries ``has_load``/``load_slot``:
  the program selects branchlessly between the live state and ring row
  ``load_slot`` per leaf (``jnp.where`` on a scalar — no host branch, no
  program variant per shape);
- after the masked fixed-``k_max`` resim, the real rows scatter back into
  the ring at ``(start_frame + 1 + i) % R`` — padded rows get slot index
  ``R`` and drop (``.at[...].set(mode="drop")``), so the scatter is
  branchless too.

The HOST keeps a slot->frame mirror: a rollback whose target frame is still
resident in the device ring fuses (1 upload + 1 dispatch services the load
AND the N replayed frames); a target that has already been overwritten —
or predates the ring — falls back to the host ring's materialize path,
which is bit-identical by construction (the device ring row IS the same
stacked row the host ring's LazySlice points at).

Bit-determinism note: the megastep is ONE fixed-shape program (fixed
``k_max``, fixed ring depth), so every flush runs the same machine code —
the same property canonical mode buys — and its checksums are pinned
bit-equal to the per-tick driver by tests/test_megastep.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..snapshot.world import Registry
from ..utils.frames import NULL_FRAME
from .resim import StepFn, resim_padded


def init_device_ring(world, slots: int):
    """Allocate the device-resident ring for ``world``'s structure: a
    ``[slots, ...]`` zeroed stacked pytree plus a ``ring_frames`` tag vector
    of ``NULL_FRAME`` (one jitted dispatch).  Unwritten rows are never
    selected — the host mirror only fuses loads for frames it has seen the
    program write."""

    def body(w):
        ring = jax.tree.map(
            lambda a: jnp.zeros((slots, *a.shape), a.dtype), w
        )
        frames = jnp.full((slots,), NULL_FRAME, jnp.int32)
        return ring, frames

    return jax.jit(body)(world)


def make_megastep_fn(reg: Registry, step_fn: StepFn, spec, fps: int,
                     seed: int = 0, retention: int = 16, k_max: int = 8,
                     ring_slots: int = 16, *, unroll: int = 1,
                     fused_checksums: bool = False):
    """Build the megastep program.

    ``fn(state, ring, ring_frames, packed int8[k_max+1, W]) ->
    (final, ring', ring_frames', stacked, checks)`` where ``packed`` is the
    ONE upload of the flush (prefix ``[start_frame, n_real, has_load,
    load_slot]`` + payload rows, ops/packing.py).  ``ring``/``ring_frames``
    are donated: the caller's handles are dead after the call and XLA
    updates the ring in place instead of copying R world snapshots per
    dispatch.  ``stacked``/``checks`` come back untrimmed at ``k_max`` rows
    (rows ``>= n_real`` carry the held state, exactly like the canonical
    padded program) so saves slice real rows without a trim dispatch."""
    from .packing import unpack_seq

    def body(state, ring, ring_frames, packed):
        inputs_seq, status_seq, start_frame, n_real, has_load, load_slot = (
            unpack_seq(spec, packed)
        )
        # branchless rollback: per leaf, pick ring row `load_slot` when the
        # prefix says so, else carry the live state (scalar-cond select —
        # both sides are resident, no host sync, one program either way)
        slot = jnp.clip(load_slot, 0, ring_slots - 1)
        loaded = jax.tree.map(
            lambda r: jax.lax.dynamic_index_in_dim(
                r, slot, axis=0, keepdims=False
            ),
            ring,
        )
        take_load = has_load != 0
        state = jax.tree.map(
            lambda a, b: jnp.where(take_load, a, b), loaded, state
        )
        final, stacked, checks = resim_padded(
            reg, step_fn, state, inputs_seq, status_seq, start_frame, n_real,
            retention, fps, seed, unroll=unroll,
            fused_checksums=fused_checksums,
        )
        # branchless ring writeback: real row i lands at frame % R; padded
        # rows get the out-of-range slot R and drop.  jnp's % follows the
        # divisor's sign, so wrapped (negative) int32 frames still map to
        # [0, R) — matching the host mirror's python `% R`.
        idx = jnp.arange(k_max, dtype=jnp.int32)
        new_frames = start_frame + jnp.int32(1) + idx
        slots = jnp.where(
            idx < n_real, new_frames % jnp.int32(ring_slots),
            jnp.int32(ring_slots),
        )
        ring = jax.tree.map(
            lambda r, s: r.at[slots].set(s, mode="drop"), ring, stacked
        )
        ring_frames = ring_frames.at[slots].set(new_frames, mode="drop")
        return final, ring, ring_frames, stacked, checks

    return jax.jit(body, donate_argnums=(1, 2))
