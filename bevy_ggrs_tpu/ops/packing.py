"""Packed single-upload staging — one int8 buffer per dispatch.

The dispatch-floor census (docs/dispatch_floor.md) showed the steady-state
P2P tick pays THREE host->device uploads per fused dispatch — ``inputs
[k, P, ...]``, ``status int8[k, P]`` and the start-frame scalar — and on a
remote-attached TPU each upload costs flat link latency, so the upload
count, not the byte count, is the tax.  This module fuses all three (plus
the megastep's load-selection words) into ONE ``int8[k + 1, W]`` buffer:

- **row 0 is the prefix**: four little-endian int32 words
  ``[start_frame, n_real, has_load, load_slot]`` occupying the first 16
  bytes (``has_load``/``load_slot`` are only read by the megastep program;
  plain packed dispatches carry zeros).
- **rows 1..k are per-frame payloads**: the frame's input bytes
  (``P * prod(input_shape) * itemsize``, raw little-endian) followed by
  the ``P`` int8 status bytes.

The host packs with numpy ``.view`` reinterpretation into a persistent
buffer (no per-tick allocation); the jitted program splits the buffer back
with ``jax.lax.bitcast_convert_type`` — a pure bit reinterpretation, so
the scan body receives exactly the arrays the three-upload path fed it and
the arithmetic is unchanged.  Both the x86 host and XLA's CPU/TPU backends
are little-endian, which is the one representation assumption the format
makes (asserted at import below).

Width is padded to ``max(payload, 16)`` rounded up to a multiple of 4 so
the prefix bitcast stays aligned and the row stride is word-aligned.
"""

from __future__ import annotations

from dataclasses import dataclass
from math import prod
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# prefix layout: int32 words [start_frame, n_real, has_load, load_slot]
PREFIX_WORDS = 4
PREFIX_BYTES = PREFIX_WORDS * 4

# the .view/bitcast round trip is only an identity on little-endian hosts;
# every supported platform (x86/arm hosts, XLA CPU/TPU backends) is LE
import sys as _sys

assert _sys.byteorder == "little", "packed staging assumes a little-endian host"


@dataclass(frozen=True)
class PackedSpec:
    """Static layout of one app's packed buffer (derived from the input
    spec; hashable so jit-side helpers can key caches on it)."""

    players: int
    input_shape: Tuple[int, ...]
    input_dtype: np.dtype
    elems: int  # per-player input elements
    in_bytes: int  # all players' input bytes per frame row
    st_bytes: int  # status bytes per frame row (== players)
    payload: int  # in_bytes + st_bytes
    width: int  # row stride (>= payload and >= PREFIX_BYTES, 4-aligned)

    @classmethod
    def from_parts(cls, players: int, input_shape, input_dtype) -> "PackedSpec":
        """Derive the row layout from the app's player count and per-player
        input shape/dtype (width 4-aligned, never below the prefix)."""
        input_shape = tuple(input_shape)
        input_dtype = np.dtype(input_dtype)
        elems = prod(input_shape) if input_shape else 1
        in_bytes = players * elems * input_dtype.itemsize
        st_bytes = players
        payload = in_bytes + st_bytes
        width = max(payload, PREFIX_BYTES)
        width = -(-width // 4) * 4
        return cls(
            players=players, input_shape=input_shape, input_dtype=input_dtype,
            elems=elems, in_bytes=in_bytes, st_bytes=st_bytes,
            payload=payload, width=width,
        )

    @classmethod
    def for_app(cls, app) -> "PackedSpec":
        return cls.from_parts(app.num_players, app.input_shape, app.input_dtype)

    def new_buffer(self, k: int) -> np.ndarray:
        """Fresh zeroed host buffer for a ``k``-frame dispatch (+prefix)."""
        return np.zeros((k + 1, self.width), np.int8)

    def new_batch_buffer(self, m: int, k: int) -> np.ndarray:
        """Per-lobby batch of packed buffers: ``int8[m, k + 1, W]``."""
        return np.zeros((m, k + 1, self.width), np.int8)


# -- host-side packing (numpy, in place) -------------------------------------

def pack_prefix(buf: np.ndarray, start_frame: int, n_real: int,
                has_load: int = 0, load_slot: int = 0) -> None:
    """Write the int32 prefix words into row 0 of ``buf`` (``int8[k+1, W]``
    or a single lane of a batch buffer).

    This is the first rewrite of every packed tick, so it is the one
    sanitizer checkpoint for the whole pack (prefix, rows, pad all rewrite
    the same backing buffer a ``guard_write`` here has already cleared)."""
    from ..utils import staging

    staging.sanitizer().guard_write(buf, "packing.pack_prefix")
    pf = buf[0, :PREFIX_BYTES].view(np.int32)
    pf[0] = start_frame
    pf[1] = n_real
    pf[2] = has_load
    pf[3] = load_slot


def pack_row(spec: PackedSpec, buf: np.ndarray, i: int, inputs, status) -> None:
    """Write frame ``i``'s input+status bytes into row ``1 + i``."""
    row = buf[1 + i]
    row[:spec.in_bytes] = (
        np.asarray(inputs, spec.input_dtype).reshape(-1).view(np.int8)
    )
    row[spec.in_bytes:spec.payload] = np.asarray(status, np.int8).reshape(-1)


def repeat_last_row(buf: np.ndarray, k: int, k_pad: int) -> None:
    """Repeat payload row ``k`` through rows ``k+1..k_pad`` (fixed-shape
    programs mask padded rows by ``n_real``; repeating the last real row
    keeps the masked arithmetic finite, matching ``pad_repeat_last``)."""
    if k_pad > k and k > 0:
        buf[1 + k:1 + k_pad] = buf[k]


# -- device-side unpacking (traced; pure bit reinterpretation) ---------------

def unpack_seq(spec: PackedSpec, packed):
    """Split one packed buffer back into the three-upload arrays inside a
    jitted program.

    ``packed`` is ``int8[k + 1, W]`` (k static from the shape).  Returns
    ``(inputs[k, P, *shape], status int8[k, P], start_frame, n_real,
    has_load, load_slot)`` — the last four as traced int32 scalars.
    ``bitcast_convert_type`` reinterprets bits without arithmetic, so the
    outputs are bit-identical to what the unpacked path uploaded."""
    k = packed.shape[0] - 1
    prefix = jax.lax.bitcast_convert_type(
        packed[0, :PREFIX_BYTES].reshape(PREFIX_WORDS, 4), jnp.int32
    )
    rows = packed[1:]
    raw = rows[:, :spec.in_bytes]
    if spec.input_dtype.itemsize == 1:
        inputs = jax.lax.bitcast_convert_type(raw, spec.input_dtype)
    else:
        inputs = jax.lax.bitcast_convert_type(
            raw.reshape(k, spec.players * spec.elems, spec.input_dtype.itemsize),
            spec.input_dtype,
        )
    inputs = inputs.reshape(k, spec.players, *spec.input_shape)
    status = rows[:, spec.in_bytes:spec.payload].reshape(k, spec.players)
    return inputs, status, prefix[0], prefix[1], prefix[2], prefix[3]
