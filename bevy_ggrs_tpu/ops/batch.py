"""Many-worlds: vmap the frame engine over a leading LOBBY axis.

The reference runs one session per process; a TPU chip is absurdly
underutilized by one small rollback sim (the 10k-entity stress world uses
<1% of v5e HBM bandwidth per frame).  This module batches M independent
game worlds — separate lobbies on a game server, a tournament bracket, an
RL population — into ONE dispatch: ``jit(vmap(lax.scan(step)))`` over a
``[M, ...]`` stacked world, with per-lobby inputs and frame counters.

Lobby independence is exact: vmap lanes share machine code, not data, so
lobby b's bits never depend on the other lanes (the same lane-independence
argument as the canonical-branched speculation program, docs/determinism.md)
— proven by the bit-equality test against M separate single-lobby runs
(tests/test_batched_lobbies.py).

Composes with the per-lobby driver loop: each lobby's session/protocol runs
host-side as usual; a server collects each lobby's pending (state, inputs)
work items and flushes them through one batched dispatch instead of M
serial ones (amortizing the per-dispatch submission cost that dominates
small worlds — docs/tpu_notes.md §3b).

Backend note: the win is an ACCELERATOR win (M submissions -> 1, and the
chip is wide enough to eat M small worlds in one pass).  On CPU, measured
8x2000-entity lobbies run ~0.8x the serial rate — XLA:CPU gains nothing
from lane-stacking tiny elementwise work; use per-lobby dispatches there.
"""

from __future__ import annotations

import time
from typing import Dict, List, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..snapshot.world import WorldState
from .resim import resim, resim_padded


def stack_worlds(worlds: List[WorldState]) -> WorldState:
    """Stack M structurally-identical worlds into one [M, ...] pytree."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *worlds)  # bgt: ignore[BGT071]: len(worlds) is the caller's lobby count — bucketed to wave capacity before dispatch, so the traced length is shape-stable per bucket


def unstack_world(batched: WorldState, i: int) -> WorldState:
    """Extract lobby ``i`` from a stacked world (one jitted dispatch)."""
    from ..snapshot.lazy import tree_index

    return tree_index(batched, i)


def make_batched_resim_fn(app):
    """jit(vmap(resim)) over the lobby axis.

    ``fn(batched_world, inputs[M, k, P, ...], status[M, k, P],
    start_frames[M]) -> (finals[M], stacked[M, k], checksums[M, k, 2])`` —
    every lobby advances k frames in one dispatch; per-lobby start frames
    keep independent clocks (lobbies need not be in lockstep).

    Refuses canonical-mode apps: canonical mode exists because the compiled
    program's shape IS a lobby-wide determinism constant for variant-
    unstable float sims (docs/determinism.md), and a vmapped M-lobby program
    is a different program than the single-lobby one the lobby's peers run —
    batching would reintroduce exactly the drift canonical mode removes.
    Integer/fixed-point and variant-stable sims (probe with
    ops/variant_probe.py) batch safely."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode: the "
            "batched program differs from the single-lobby canonical "
            "program every peer dispatches, breaking the one-program "
            "bit-determinism guarantee (see make_batched_resim_fn docstring)"
        )
    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention

    @jax.jit
    def fn(batched_world, inputs_b, status_b, start_frames):
        return jax.vmap(
            lambda w, inp, st, f: resim(
                reg, step, w, inp, st, f, retention, fps, seed
            )
        )(batched_world, inputs_b, status_b, start_frames)

    return fn


def make_batched_padded_fn(app, k_max: int, donate: bool = False, *,
                           unroll: int = 1, fused_checksums: bool = False):
    """jit(vmap(resim_padded)) over the lobby axis — the BatchedRunner's
    dispatch: every lobby advances up to ``k_max`` frames in ONE call, with
    per-lobby ``n_real`` masking (a lobby with no pending work passes its
    lane through unchanged at ``n_real=0``).

    ``fn(batched_world[M], inputs[M, k_max, P, ...], status[M, k_max, P],
    start_frames[M], n_real[M]) -> (finals[M], stacked[M, k_max],
    checksums_flat[M * k_max, 2])`` — checksums come out pre-flattened so
    one BatchChecks wraps the whole dispatch (row ``b * k_max + i``).

    Same canonical-mode refusal (and rationale) as
    :func:`make_batched_resim_fn`.  ``donate=True`` donates the batched
    world for in-place lane updates (the server's resident-world fast
    path).  ``unroll``/``fused_checksums`` forward to
    :func:`..ops.resim.resim_padded` (defaults = the historical program)."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode "
            "(see make_batched_resim_fn)"
        )
    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention

    def body(batched_world, inputs_b, status_b, start_frames, n_real):
        finals, stacked, checks = jax.vmap(
            lambda w, inp, st, f, nr: resim_padded(
                reg, step, w, inp, st, f, nr, retention, fps, seed,
                unroll=unroll, fused_checksums=fused_checksums,
            )
        )(batched_world, inputs_b, status_b, start_frames, n_real)
        return finals, stacked, checks.reshape(-1, 2)

    return jax.jit(body, donate_argnums=(0,) if donate else ())


def make_batched_exact_fn(app, k: int, *, unroll: int = 1,
                          fused_checksums: bool = False,
                          donate_outputs: bool = False):
    """jit(vmap(resim)) at an EXACT depth ``k`` — the unmasked full-wave
    program.

    When every active lane advances exactly ``k`` frames the per-frame
    ``n_real`` mask of :func:`make_batched_padded_fn` buys nothing and costs
    a full-world select per frame (~12% of the batched tick on the CPU
    reference host); this builder drops it.  Signature:
    ``fn(batched_world[M], inputs[M, k, P, ...], status[M, k, P],
    start_frames[M]) -> (finals[M], stacked[M, k], checks_flat[M*k, 2])``.

    ``donate_outputs=True`` appends two dummy parameters
    ``(prev_stacked, prev_checks)`` — the PREVIOUS call's stacked/checks
    outputs — marked as donated: XLA aliases their buffers onto this call's
    outputs, so the steady-state wave loop recycles its two big output
    allocations instead of churning the host allocator every dispatch
    (measured +10-15% agg throughput and a 4-8x spread reduction on the
    1-CPU bench host).  Callers own the aliasing contract: the passed
    previous outputs are DEAD after the call (see
    :class:`BucketedWaveExecutor`, which manages this automatically)."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode "
            "(see make_batched_resim_fn)"
        )
    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention

    def core(batched_world, inputs_b, status_b, start_frames):
        finals, stacked, checks = jax.vmap(
            lambda w, inp, st, f: resim(
                reg, step, w, inp, st, f, retention, fps, seed,
                unroll=unroll, fused_checksums=fused_checksums,
            )
        )(batched_world, inputs_b, status_b, start_frames)
        return finals, stacked, checks.reshape(-1, 2)

    if not donate_outputs:
        return jax.jit(core)

    def recycling(batched_world, inputs_b, status_b, start_frames,
                  prev_stacked, prev_checks):
        del prev_stacked, prev_checks  # donated for output aliasing only
        return core(batched_world, inputs_b, status_b, start_frames)

    return jax.jit(recycling, donate_argnums=(4, 5))


def make_batched_packed_padded_fn(app, k: int, *, unroll: int = 1,
                                  fused_checksums: bool = False):
    """Packed single-upload variant of :func:`make_batched_padded_fn`:
    ``fn(batched_world[M], packed int8[M, k + 1, W]) -> (finals[M],
    stacked[M, k], checks_flat[M * k, 2])``.

    Each lane's prefix row carries its OWN ``(start_frame, n_real)`` (ops/
    packing.py), so the per-lobby start-frame and mask vectors that used to
    ride as separate uploads are folded into the one buffer — a wave costs
    one host->device upload total instead of four.  The unpack is a pure
    bitcast; arithmetic is unchanged, so lanes stay bit-identical to the
    unpacked program (tests/test_packed.py)."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode "
            "(see make_batched_resim_fn)"
        )
    from .packing import unpack_seq

    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention
    pspec = app.packed_spec

    def lane(w, pk):
        inputs, status, start, n_real, _hl, _ls = unpack_seq(pspec, pk)
        return resim_padded(
            reg, step, w, inputs, status, start, n_real, retention, fps,
            seed, unroll=unroll, fused_checksums=fused_checksums,
        )

    def body(batched_world, packed_b):
        finals, stacked, checks = jax.vmap(lane)(batched_world, packed_b)
        return finals, stacked, checks.reshape(-1, 2)

    return jax.jit(body)


def make_batched_packed_exact_fn(app, k: int, *, unroll: int = 1,
                                 fused_checksums: bool = False,
                                 donate_outputs: bool = False):
    """Packed single-upload variant of :func:`make_batched_exact_fn` (the
    unmasked full-wave program): ``fn(batched_world[M],
    packed int8[M, k + 1, W]) -> (finals, stacked, checks_flat)``; the
    per-lane prefix supplies the start frame (``n_real`` is ignored — every
    lane advances exactly ``k``).  ``donate_outputs=True`` appends the
    previous call's ``(prev_stacked, prev_checks)`` as donated parameters,
    same recycling contract as the unpacked builder."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode "
            "(see make_batched_resim_fn)"
        )
    from .packing import unpack_seq

    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention
    pspec = app.packed_spec

    def lane(w, pk):
        inputs, status, start, _nr, _hl, _ls = unpack_seq(pspec, pk)
        return resim(
            reg, step, w, inputs, status, start, retention, fps, seed,
            unroll=unroll, fused_checksums=fused_checksums,
        )

    def core(batched_world, packed_b):
        finals, stacked, checks = jax.vmap(lane)(batched_world, packed_b)
        return finals, stacked, checks.reshape(-1, 2)

    if not donate_outputs:
        return jax.jit(core)

    def recycling(batched_world, packed_b, prev_stacked, prev_checks):
        del prev_stacked, prev_checks  # donated for output aliasing only
        return core(batched_world, packed_b)

    return jax.jit(recycling, donate_argnums=(2, 3))


def bucket_sizes(k_max: int) -> Tuple[int, ...]:
    """Power-of-two depth buckets up to (and always including) ``k_max``:
    ``bucket_sizes(12) == (1, 2, 4, 8, 12)``.  A wave whose hottest lobby
    advances ``k_hot`` frames dispatches the smallest bucket >= k_hot, so
    the compile count is O(log k_max) while a typical 1-advance lockstep
    wave stops paying for a k_max-frame scan."""
    if k_max < 1:
        raise ValueError(f"k_max must be >= 1, got {k_max}")
    sizes = []
    b = 1
    while b < k_max:
        sizes.append(b)
        b *= 2
    sizes.append(k_max)
    return tuple(sizes)


class BucketedWaveExecutor:
    """Shape-bucketed dispatcher for BatchedRunner waves.

    The old hot loop compiled ONE ``k_max``-deep padded program and ran every
    wave through it — a 1-advance lockstep tick scanned ``k_max`` frames per
    lobby with all but one masked off.  This executor keeps a small cache of
    programs keyed by ``(kind, bucket)``:

    - ``bucket`` ∈ :func:`bucket_sizes(k_max)` — the smallest power-of-two
      depth covering the wave's ``k_hot``, so the wasted scan length is < 2x
      and the compile count is O(log k_max), not O(k_max) (jit itself adds a
      (M, world-spec) axis to the cache key: a new lobby count or world
      structure retraces, same shapes hit).
    - ``kind`` — ``exact`` when every lane advances exactly ``bucket`` frames
      (no mask, ~12% faster) or ``padded`` (per-lane ``n_real`` masking) for
      ragged/partial waves.

    All programs run ``unroll=2`` scans with the checksum reduction hoisted
    out of the scan body; both are bit-identical transformations for the
    repo's uint32 wrapping-add checksum (see ``ops/resim.resim``), and exact
    vs padded equality for variant-stable sims is covered by
    tests/test_batched_runner.py.

    ``recycle_outputs=True`` additionally routes full waves through the
    donating program of :func:`make_batched_exact_fn`, recycling the
    previous wave's stacked/checks buffers into the new outputs.  Only
    enable it when NOTHING retains those outputs across calls — the
    BatchedRunner can't (its snapshot rings hold LazySlice handles into
    past stacked buffers), the throughput bench can and does.

    Dispatch/compile behavior is observable three ways: the
    ``batched_wave_dispatches_total`` / ``batched_program_compiles_total``
    telemetry counters (pre-bound, argument-free), the plain-int
    ``dispatch_count`` / ``compile_count`` attributes, and the per-bucket
    histogram from :meth:`stats`.
    """

    def __init__(self, app, k_max: int, *, unroll: int = 2,
                 fused_checksums: bool = True, recycle_outputs: bool = False):
        if app.canonical_depth is not None or app.canonical_branches is not None:
            raise ValueError(
                "many-worlds batching is incompatible with canonical mode "
                "(see make_batched_resim_fn)"
            )
        self.app = app
        self.k_max = int(k_max)
        self.unroll = unroll
        self.fused_checksums = fused_checksums
        self.recycle_outputs = recycle_outputs
        self.buckets = bucket_sizes(self.k_max)
        self._fns: Dict[Tuple[str, int], object] = {}
        self._prev_out: Dict[Tuple[str, int], tuple] = {}
        self.compile_count = 0  # programs built (per (kind, bucket))
        self.dispatch_count = 0
        self.bucket_hist: Dict[int, int] = {b: 0 for b in self.buckets}
        # first-dispatch wall time per program variant: jit compiles lazily,
        # so the first call of each (kind, bucket) pays trace+compile — the
        # device-time attribution bench/stats surface (keys "exact_k4", ...)
        self.compile_ms: Dict[str, float] = {}
        self._timed: Set[Tuple[str, int]] = set()
        self._owner = "wave"
        from .. import telemetry

        _reg = telemetry.registry()
        self._m_dispatches = _reg.bind_counter(
            "batched_wave_dispatches_total",
            "wave dispatches through the bucketed executor",
        )
        self._m_compiles = _reg.bind_counter(
            "batched_program_compiles_total",
            "bucketed wave programs built (kind x bucket)",
        )
        # upload census (same family the solo runner binds): run_wave_packed
        # issues ONE upload per wave; the unpacked run_wave issues 3 (4 for
        # ragged waves, which add the n_real vector)
        self.host_uploads = 0
        self.packed_upload_bytes = 0
        self._m_uploads = _reg.bind_histogram(
            "uploads_per_dispatch",
            "host->device uploads issued per fused dispatch (1 on the "
            "packed path)",
            buckets=(1, 2, 3, 4, 8),
        )
        self._m_packed_bytes = _reg.bind_counter(
            "packed_upload_bytes",
            "bytes staged through packed single-upload buffers",
        )

    def _note_uploads(self, n: int, packed_buf=None) -> None:
        self.host_uploads += n
        self._m_uploads.observe(n)
        if packed_buf is not None:
            self.packed_upload_bytes += packed_buf.nbytes
            self._m_packed_bytes.inc(packed_buf.nbytes)

    def bucket_for(self, k_hot: int) -> int:
        """Smallest bucket >= ``k_hot`` (raises beyond ``k_max``)."""
        if k_hot > self.k_max:
            raise ValueError(
                f"wave depth {k_hot} exceeds k_max={self.k_max}"
            )
        for b in self.buckets:
            if b >= k_hot:
                return b
        raise AssertionError("unreachable: buckets end at k_max")

    def _get_fn(self, kind: str, bucket: int):
        fn = self._fns.get((kind, bucket))
        if fn is None:
            if kind == "exact":
                fn = make_batched_exact_fn(
                    self.app, bucket, unroll=self.unroll,
                    fused_checksums=self.fused_checksums,
                )
            elif kind == "exact_recycle":
                fn = make_batched_exact_fn(
                    self.app, bucket, unroll=self.unroll,
                    fused_checksums=self.fused_checksums, donate_outputs=True,
                )
            elif kind == "packed_exact":
                fn = make_batched_packed_exact_fn(
                    self.app, bucket, unroll=self.unroll,
                    fused_checksums=self.fused_checksums,
                )
            elif kind == "packed_exact_recycle":
                fn = make_batched_packed_exact_fn(
                    self.app, bucket, unroll=self.unroll,
                    fused_checksums=self.fused_checksums, donate_outputs=True,
                )
            elif kind == "packed_padded":
                fn = make_batched_packed_padded_fn(
                    self.app, bucket, unroll=self.unroll,
                    fused_checksums=self.fused_checksums,
                )
            else:
                fn = make_batched_padded_fn(
                    self.app, bucket, unroll=self.unroll,
                    fused_checksums=self.fused_checksums,
                )
            self._fns[(kind, bucket)] = fn
            self.compile_count += 1
            self._m_compiles.inc()
        return fn

    def _dispatch(self, kind: str, bucket: int, *args):
        """Call the ``(kind, bucket)`` wave program, timing its FIRST call.

        jit returns instantly at build time and compiles at first dispatch,
        so that call's wall time IS the program's trace+compile cost; it
        lands in :attr:`compile_ms`, the flight recorder and (telemetry on)
        the ``program_compile_ms`` histogram.  Steady-state overhead over a
        raw ``_get_fn(...)(...)`` call: one extra set lookup."""
        key = (kind, bucket)
        if key in self._timed:
            return self._fns[key](*args)
        fn = self._get_fn(kind, bucket)
        t0 = time.perf_counter()
        out = fn(*args)
        ms = (time.perf_counter() - t0) * 1e3
        self._timed.add(key)
        self.compile_ms[f"{kind}_k{bucket}"] = round(ms, 3)
        from .. import telemetry

        telemetry.flight_recorder().record(
            "compile", owner=self._owner, program=kind, k=bucket,
            ms=round(ms, 3),
        )
        telemetry.observe(
            "program_compile_ms", ms,
            "wall ms of each program variant's first dispatch (trace+compile)",
            buckets=telemetry.LATENCY_MS_BUCKETS,
            owner=self._owner, kind=kind,
        )
        from ..utils import compile_guard

        compile_guard.notify(self._owner, kind, ms)
        return out

    def run_wave(self, worlds, inputs, status, starts, ks):
        """Dispatch one wave; returns ``(bucket, finals, stacked,
        checks_flat)``.

        ``inputs``/``status`` are the full ``[M, >=bucket, ...]`` staging
        buffers (host or device); the executor slices ``[:, :bucket]``
        itself.  ``ks`` is the per-lobby advance count (0 = idle lane);
        ``checks_flat`` rows are ``b * bucket + i``."""
        ks = list(ks)
        k_hot = max(ks)
        if k_hot <= 0:
            raise ValueError("run_wave needs at least one advancing lobby")
        bucket = self.bucket_for(k_hot)
        exact = all(k == bucket for k in ks)
        # persistent staging buffers are rewritten next wave: commit the
        # sliced uploads synchronously so the asynchronous transfer can
        # never read a later wave's bytes (utils/staging.py)
        from ..utils import staging
        from ..utils.staging import commit

        inp = commit(inputs[:, :bucket])
        st = commit(status[:, :bucket])
        starts = commit(np.asarray(starts, np.int32))
        self.dispatch_count += 1
        self.bucket_hist[bucket] += 1
        self._m_dispatches.inc()
        if exact:
            self._note_uploads(3)
            if self.recycle_outputs:
                key = ("exact_recycle", bucket)
                prev = self._prev_out.pop(key, None)
                if prev is None:
                    # first call at this bucket: nothing to recycle yet
                    finals, stacked, checks = self._dispatch(
                        "exact", bucket, worlds, inp, st, starts
                    )
                else:
                    san = staging.sanitizer()
                    san.guard_donated(prev[0], "batch.run_wave/stacked")
                    san.guard_donated(prev[1], "batch.run_wave/checks")
                    finals, stacked, checks = self._dispatch(
                        *key, worlds, inp, st, starts, *prev
                    )
                    # the dispatch donated prev's device buffers: any
                    # later reuse of those handles is a race
                    san.donate(prev[0], "exact_recycle stacked")
                    san.donate(prev[1], "exact_recycle checks")
                self._prev_out[key] = (stacked, checks)
            else:
                finals, stacked, checks = self._dispatch(
                    "exact", bucket, worlds, inp, st, starts
                )
        else:
            self._note_uploads(4)
            n_real = np.asarray(ks, np.int32)
            finals, stacked, checks = self._dispatch(
                "padded", bucket, worlds, inp, st, starts, n_real
            )
        return bucket, finals, stacked, checks

    def run_wave_packed(self, worlds, packed, ks):
        """Dispatch one wave fed by the packed single-upload staging buffer
        ``packed int8[M, >= bucket + 1, W]`` (per-lane prefix row carries
        that lobby's start frame and ``n_real`` — ops/packing.py); same
        return contract as :meth:`run_wave`.  The whole wave costs ONE
        host->device upload (the resident stacked world never leaves the
        device)."""
        ks = list(ks)
        k_hot = max(ks)
        if k_hot <= 0:
            raise ValueError("run_wave needs at least one advancing lobby")
        bucket = self.bucket_for(k_hot)
        exact = all(k == bucket for k in ks)
        from ..utils import staging
        from ..utils.staging import commit

        pk = commit(packed[:, :bucket + 1])
        self.dispatch_count += 1
        self.bucket_hist[bucket] += 1
        self._m_dispatches.inc()
        self._note_uploads(1, pk)
        if exact:
            if self.recycle_outputs:
                key = ("packed_exact_recycle", bucket)
                prev = self._prev_out.pop(key, None)
                if prev is None:
                    finals, stacked, checks = self._dispatch(
                        "packed_exact", bucket, worlds, pk
                    )
                else:
                    san = staging.sanitizer()
                    san.guard_donated(prev[0], "batch.run_wave_packed/stacked")
                    san.guard_donated(prev[1], "batch.run_wave_packed/checks")
                    finals, stacked, checks = self._dispatch(
                        *key, worlds, pk, *prev
                    )
                    san.donate(prev[0], "packed_exact_recycle stacked")
                    san.donate(prev[1], "packed_exact_recycle checks")
                self._prev_out[key] = (stacked, checks)
            else:
                finals, stacked, checks = self._dispatch(
                    "packed_exact", bucket, worlds, pk
                )
        else:
            finals, stacked, checks = self._dispatch(
                "packed_padded", bucket, worlds, pk
            )
        return bucket, finals, stacked, checks

    def stats(self) -> dict:
        """Executor-side counters for bench/tests: dispatches, compiles,
        per-bucket dispatch histogram, live jit cache entries."""
        jit_entries = 0
        for fn in self._fns.values():
            try:
                jit_entries += fn._cache_size()
            except Exception:
                pass
        return {
            "wave_dispatches": self.dispatch_count,
            "program_compiles": self.compile_count,
            "bucket_hist": {k: v for k, v in self.bucket_hist.items() if v},
            "jit_entries": jit_entries,
            "compile_ms": dict(self.compile_ms),
            "host_uploads": self.host_uploads,
            "packed_upload_bytes": self.packed_upload_bytes,
        }


# -- device-sharded many-worlds executor -------------------------------------

def make_sharded_padded_fn(app, k: int, mesh, *, unroll: int = 1,
                           fused_checksums: bool = False):
    """The ``n_real``-masked bucketed wave program sharded over a
    ``"lobby"`` mesh axis via ``shard_map``.

    Each device receives its contiguous ``M/D`` block of lobby lanes and
    runs ``vmap(resim_padded)`` over them — the SAME SPMD program on every
    device, so a wave of M lobbies on D devices costs one dispatch per
    device instead of one device doing all M lanes.  Lobbies never
    communicate, so the body contains NO collectives; the checksum
    post-pass (``fused_checksums``) runs per-lane inside the shard, which
    keeps it bit-exact (the uint32 wrapping-add reduction never crosses a
    shard boundary).  Signature matches :func:`make_batched_padded_fn`
    with M divisible by the mesh size (the executor pads)."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode "
            "(see make_batched_resim_fn)"
        )
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import LOBBY_AXIS

    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention
    spec = P(LOBBY_AXIS)

    def local(batched_world, inputs_b, status_b, start_frames, n_real):
        return jax.vmap(
            lambda w, inp, st, f, nr: resim_padded(
                reg, step, w, inp, st, f, nr, retention, fps, seed,
                unroll=unroll, fused_checksums=fused_checksums,
            )
        )(batched_world, inputs_b, status_b, start_frames, n_real)

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec),
        out_specs=(spec, spec, spec),
        check_rep=False,  # no replication to track: lanes are independent
    )

    def body(batched_world, inputs_b, status_b, start_frames, n_real):
        finals, stacked, checks = sharded(
            batched_world, inputs_b, status_b, start_frames, n_real
        )
        return finals, stacked, checks.reshape(-1, 2)

    return jax.jit(body)


def make_sharded_exact_fn(app, k: int, mesh, *, unroll: int = 1,
                          fused_checksums: bool = False):
    """Exact-depth (unmasked) wave program over the ``"lobby"`` mesh axis —
    the sharded analog of :func:`make_batched_exact_fn` (no
    ``donate_outputs`` variant: output recycling and cross-device layout
    donation do not compose safely, and the sharded path's win is dispatch
    parallelism, not allocator churn)."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode "
            "(see make_batched_resim_fn)"
        )
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import LOBBY_AXIS

    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention
    spec = P(LOBBY_AXIS)

    def local(batched_world, inputs_b, status_b, start_frames):
        return jax.vmap(
            lambda w, inp, st, f: resim(
                reg, step, w, inp, st, f, retention, fps, seed,
                unroll=unroll, fused_checksums=fused_checksums,
            )
        )(batched_world, inputs_b, status_b, start_frames)

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, spec, spec),
        check_rep=False,
    )

    def body(batched_world, inputs_b, status_b, start_frames):
        finals, stacked, checks = sharded(
            batched_world, inputs_b, status_b, start_frames
        )
        return finals, stacked, checks.reshape(-1, 2)

    return jax.jit(body)


def make_sharded_packed_padded_fn(app, k: int, mesh, *, unroll: int = 1,
                                  fused_checksums: bool = False):
    """Packed single-upload variant of :func:`make_sharded_padded_fn`:
    ``fn(batched_world[M], packed int8[M, k + 1, W])`` with both arguments
    sharded over the ``"lobby"`` mesh axis.  Each device unpacks its own
    block of lanes (prefix bitcast is per-lane, no collectives)."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode "
            "(see make_batched_resim_fn)"
        )
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import LOBBY_AXIS
    from .packing import unpack_seq

    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention
    pspec = app.packed_spec
    spec = P(LOBBY_AXIS)

    def lane(w, pk):
        inputs, status, start, n_real, _hl, _ls = unpack_seq(pspec, pk)
        return resim_padded(
            reg, step, w, inputs, status, start, n_real, retention, fps,
            seed, unroll=unroll, fused_checksums=fused_checksums,
        )

    def local(batched_world, packed_b):
        return jax.vmap(lane)(batched_world, packed_b)

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec),
        out_specs=(spec, spec, spec),
        check_rep=False,  # no replication to track: lanes are independent
    )

    def body(batched_world, packed_b):
        finals, stacked, checks = sharded(batched_world, packed_b)
        return finals, stacked, checks.reshape(-1, 2)

    return jax.jit(body)


def make_sharded_packed_exact_fn(app, k: int, mesh, *, unroll: int = 1,
                                 fused_checksums: bool = False):
    """Packed single-upload variant of :func:`make_sharded_exact_fn` (no
    recycling variant, same rationale as the unpacked sharded builder)."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode "
            "(see make_batched_resim_fn)"
        )
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import LOBBY_AXIS
    from .packing import unpack_seq

    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention
    pspec = app.packed_spec
    spec = P(LOBBY_AXIS)

    def lane(w, pk):
        inputs, status, start, _nr, _hl, _ls = unpack_seq(pspec, pk)
        return resim(
            reg, step, w, inputs, status, start, retention, fps, seed,
            unroll=unroll, fused_checksums=fused_checksums,
        )

    def local(batched_world, packed_b):
        return jax.vmap(lane)(batched_world, packed_b)

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec),
        out_specs=(spec, spec, spec),
        check_rep=False,
    )

    def body(batched_world, packed_b):
        finals, stacked, checks = sharded(batched_world, packed_b)
        return finals, stacked, checks.reshape(-1, 2)

    return jax.jit(body)


class ShardedWaveExecutor(BucketedWaveExecutor):
    """:class:`BucketedWaveExecutor` whose wave programs shard the lobby
    axis over a device mesh — the many-lobbies-across-the-mesh executor
    (docs/architecture.md "Many-worlds sharding").

    Same bucket/kind cache and :meth:`run_wave` contract as the parent;
    the differences:

    - programs come from :func:`make_sharded_padded_fn` /
      :func:`make_sharded_exact_fn`: one SPMD dispatch drives every device,
      each owning a contiguous ``M_pad / D`` block of lobby lanes;
    - waves whose lobby count M is NOT divisible by the device count D are
      padded to ``M_pad = ceil(M/D) * D`` with idle lanes (``n_real = 0``
      — masked out by the padded program) and the outputs are trimmed back
      to M rows in one extra jitted dispatch.  Callers that control their
      resident world (BatchedRunner) pre-pad to M_pad so the steady state
      never pays the pad/trim pair;
    - ``recycle_outputs`` is refused (donating sharded outputs across waves
      is not supported);
    - dispatch/compile counts surface through the
      ``sharded_wave_dispatches_total`` / ``shard_program_compiles_total``
      telemetry counters (pre-bound) alongside the parent's plain-int
      attributes, and :meth:`stats` adds the device count.

    Bit-exactness: shard_map hands each device the identical per-lane
    program the unsharded vmap runs, and lanes never communicate, so for
    variant-stable sims the sharded wave is bit-identical to the unsharded
    one — enforced by tests/test_sharded_wave.py against
    :class:`BucketedWaveExecutor` on identical waves.
    """

    def __init__(self, app, k_max: int, mesh, *, unroll: int = 2,
                 fused_checksums: bool = True, recycle_outputs: bool = False):
        if recycle_outputs:
            raise ValueError(
                "ShardedWaveExecutor does not support recycle_outputs "
                "(cross-wave donation of lobby-sharded buffers)"
            )
        super().__init__(app, k_max, unroll=unroll,
                         fused_checksums=fused_checksums)
        self.mesh = mesh
        self.n_devices = int(mesh.devices.size)
        self._owner = "sharded"
        from .. import telemetry

        _reg = telemetry.registry()
        self._m_sharded_dispatches = _reg.bind_counter(
            "sharded_wave_dispatches_total",
            "wave dispatches through the lobby-sharded executor",
        )
        self._m_shard_compiles = _reg.bind_counter(
            "shard_program_compiles_total",
            "lobby-sharded wave programs built (kind x bucket)",
        )
        self._trim_fns: Dict[Tuple[int, int, int], object] = {}
        # staging commits land lobby-axis-sharded so the shard_map programs
        # read device-local rows with no reshard (utils/staging.py)
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as _P

        from ..parallel.mesh import LOBBY_AXIS

        self._stage_sharding = NamedSharding(mesh, _P(LOBBY_AXIS))

    def _commit_sharded(self, arr):
        """Synchronous lobby-sharded upload of a (reused) staging buffer —
        same rewrite-race rationale as the parent's plain commits."""
        from ..utils.staging import commit

        return commit(arr, self._stage_sharding)

    def pad_lobbies(self, m: int) -> int:
        """Smallest multiple of the device count >= ``m``."""
        d = self.n_devices
        return -(-m // d) * d

    def _get_fn(self, kind: str, bucket: int):
        fn = self._fns.get((kind, bucket))
        if fn is None:
            if kind == "exact":
                fn = make_sharded_exact_fn(
                    self.app, bucket, self.mesh, unroll=self.unroll,
                    fused_checksums=self.fused_checksums,
                )
            elif kind == "padded":
                fn = make_sharded_padded_fn(
                    self.app, bucket, self.mesh, unroll=self.unroll,
                    fused_checksums=self.fused_checksums,
                )
            elif kind == "packed_exact":
                fn = make_sharded_packed_exact_fn(
                    self.app, bucket, self.mesh, unroll=self.unroll,
                    fused_checksums=self.fused_checksums,
                )
            elif kind == "packed_padded":
                fn = make_sharded_packed_padded_fn(
                    self.app, bucket, self.mesh, unroll=self.unroll,
                    fused_checksums=self.fused_checksums,
                )
            else:  # pragma: no cover - parent never asks for *_recycle here
                raise ValueError(f"sharded executor has no {kind!r} programs")
            self._fns[(kind, bucket)] = fn
            self.compile_count += 1
            self._m_compiles.inc()
            self._m_shard_compiles.inc()
        return fn

    def run_wave(self, worlds, inputs, status, starts, ks):
        """Dispatch one lobby-sharded wave (same contract as the parent:
        returns ``(bucket, finals, stacked, checks_flat)`` with
        ``checks_flat`` rows at ``b * bucket + i`` over the CALLER's M
        lobbies).  Pads M to a device-count multiple when needed; padded
        lanes ride the masked program at ``n_real = 0`` and are trimmed
        from the outputs before returning."""
        ks = list(ks)
        m = len(ks)
        m_pad = self.pad_lobbies(m)
        pad = m_pad - m
        if pad:
            worlds = _pad_rows(worlds, pad)
            inputs = np.concatenate(
                [inputs, np.broadcast_to(inputs[-1:], (pad, *inputs.shape[1:]))]
            )
            status = np.concatenate(
                [status, np.broadcast_to(status[-1:], (pad, *status.shape[1:]))]
            )
            starts = np.concatenate(
                [np.asarray(starts, np.int32), np.zeros((pad,), np.int32)]
            )
            ks = ks + [0] * pad
        k_hot = max(ks)
        if k_hot <= 0:
            raise ValueError("run_wave needs at least one advancing lobby")
        bucket = self.bucket_for(k_hot)
        exact = all(k == bucket for k in ks)
        inp = self._commit_sharded(np.ascontiguousarray(inputs[:, :bucket]))
        st = self._commit_sharded(np.ascontiguousarray(status[:, :bucket]))
        starts = self._commit_sharded(np.asarray(starts, np.int32))
        self.dispatch_count += 1
        self.bucket_hist[bucket] += 1
        self._m_dispatches.inc()
        self._m_sharded_dispatches.inc()
        if exact:
            self._note_uploads(3)
            finals, stacked, checks = self._dispatch(
                "exact", bucket, worlds, inp, st, starts
            )
        else:
            self._note_uploads(4)
            n_real = np.asarray(ks, np.int32)
            finals, stacked, checks = self._dispatch(
                "padded", bucket, worlds, inp, st, starts, n_real
            )
        if pad:
            finals, stacked, checks = self._trim_wave(
                finals, stacked, checks, m, m_pad, bucket
            )
        return bucket, finals, stacked, checks

    def run_wave_packed(self, worlds, packed, ks):
        """Packed single-upload sharded wave (same contract as the parent's
        :meth:`run_wave_packed`).  Padded lobby lanes get a zeroed prefix
        (``n_real = 0``) so the masked program passes them through — the
        pad block is built host-side, so the wave still costs ONE upload."""
        ks = list(ks)
        m = len(ks)
        m_pad = self.pad_lobbies(m)
        pad = m_pad - m
        if pad:
            from .packing import pack_prefix

            worlds = _pad_rows(worlds, pad)
            pad_block = np.repeat(packed[-1:], pad, axis=0)
            for r in range(pad):
                pack_prefix(pad_block[r], 0, 0)
            packed = np.concatenate([packed, pad_block])
            ks = ks + [0] * pad
        k_hot = max(ks)
        if k_hot <= 0:
            raise ValueError("run_wave needs at least one advancing lobby")
        bucket = self.bucket_for(k_hot)
        exact = all(k == bucket for k in ks)
        pk = self._commit_sharded(np.ascontiguousarray(packed[:, :bucket + 1]))
        self.dispatch_count += 1
        self.bucket_hist[bucket] += 1
        self._m_dispatches.inc()
        self._m_sharded_dispatches.inc()
        self._note_uploads(1, pk)
        if exact:
            finals, stacked, checks = self._dispatch(
                "packed_exact", bucket, worlds, pk
            )
        else:
            finals, stacked, checks = self._dispatch(
                "packed_padded", bucket, worlds, pk
            )
        if pad:
            finals, stacked, checks = self._trim_wave(
                finals, stacked, checks, m, m_pad, bucket
            )
        return bucket, finals, stacked, checks

    def _trim_wave(self, finals, stacked, checks, m, m_pad, bucket):
        """Drop the padded lobby rows from a wave's outputs (ONE jitted
        dispatch for the whole triple, compiled per (m, m_pad, bucket))."""
        fn = self._trim_fns.get((m, m_pad, bucket))
        if fn is None:

            def body(fin, stk, chk):
                fin = jax.tree.map(lambda a: a[:m], fin)
                stk = jax.tree.map(lambda a: a[:m], stk)
                chk = chk.reshape(m_pad, bucket, 2)[:m].reshape(-1, 2)
                return fin, stk, chk

            fn = self._trim_fns[(m, m_pad, bucket)] = jax.jit(body)
        return fn(finals, stacked, checks)

    def harvest_shards(self, outputs) -> dict:
        """Block until a wave's outputs have retired on EVERY device and
        report the per-shard layout: device count, lanes per device, and
        per-device buffer residency.  This is the sharded bench stage's
        per-device metrics probe — an allowlisted hot-loop purity flush
        point (scripts/lint_imports.py): never call it from the steady-state
        dispatch path."""
        jax.block_until_ready(outputs)
        leaves = jax.tree.leaves(outputs)
        per_dev: Dict[str, int] = {}
        for leaf in leaves:
            shards = getattr(leaf, "addressable_shards", None)
            if not shards:
                continue
            for s in shards:
                key = str(s.device)
                per_dev[key] = per_dev.get(key, 0) + 1
        return {
            "n_devices": self.n_devices,
            "devices_touched": len(per_dev),
            "buffers_per_device": per_dev,
        }

    def stats(self) -> dict:
        """Parent counters plus ``shard_devices`` (mesh size)."""
        out = super().stats()
        out["shard_devices"] = self.n_devices
        return out


_pad_rows_jits: Dict[int, object] = {}


def _pad_rows(tree, pad: int):
    """Extend every leaf's leading (lobby) axis by ``pad`` rows repeating
    row 0 (ONE jitted dispatch, compiled per pad count x tree shape).  The
    pad lanes only ever run masked (``n_real = 0``) so their content is
    irrelevant — repeating a real row keeps the arithmetic finite."""
    fn = _pad_rows_jits.get(pad)
    if fn is None:

        def body(t):
            return jax.tree.map(
                lambda a: jnp.concatenate(
                    [a, jnp.broadcast_to(a[:1], (pad, *a.shape[1:]))]
                ),
                t,
            )

        fn = _pad_rows_jits[pad] = jax.jit(body)
    return fn(tree)


class DraftWaveScheduler:
    """Assign speculative draft branches to the wave lanes the active bucket
    left idle (BatchedRunner speculation — docs/architecture.md "Speculative
    rollback servicing").

    The batched tick's run wave only occupies the lanes of lobbies that
    advanced this tick with ``ks[b] > 0``; the rest of the ``[M, ...]``
    dispatch is dead weight.  ``plan()`` fills exactly those idle lanes with
    candidate branches — round-robin across the drafting lobbies so one
    lobby's wide candidate fan cannot starve the rest — and NEVER touches an
    active lane, so the draft wave's lane census is disjoint from the real
    wave's by construction.  Candidates that do not fit this tick are
    dropped (counted in ``dropped_candidates``), not queued: a stale draft
    for a frame the session has moved past can never be looked up again."""

    def __init__(self, m_pad: int):
        self.m_pad = int(m_pad)
        self.waves_planned = 0
        self.lanes_filled = 0
        self.dropped_candidates = 0

    def plan(
        self, idle_lanes: List[int], wants: List[Tuple[int, int]]
    ) -> List[Tuple[int, int, int]]:
        """``wants`` is ``[(lobby, n_candidates)]``; returns assignments
        ``[(lobby, candidate_index, lane)]`` using at most the given idle
        lanes."""
        lanes = list(idle_lanes)
        queues = [[b, 0, n] for b, n in wants if n > 0]  # lobby, next, total
        out: List[Tuple[int, int, int]] = []
        qi = 0
        while lanes and queues:
            if qi >= len(queues):
                qi = 0
            b, nxt, total = queues[qi]
            out.append((b, nxt, lanes.pop(0)))
            queues[qi][1] = nxt + 1
            if nxt + 1 >= total:
                queues.pop(qi)
            else:
                qi += 1
        self.waves_planned += 1
        self.lanes_filled += len(out)
        self.dropped_candidates += sum(t - n for _b, n, t in queues)
        return out
