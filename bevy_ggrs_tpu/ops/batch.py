"""Many-worlds: vmap the frame engine over a leading LOBBY axis.

The reference runs one session per process; a TPU chip is absurdly
underutilized by one small rollback sim (the 10k-entity stress world uses
<1% of v5e HBM bandwidth per frame).  This module batches M independent
game worlds — separate lobbies on a game server, a tournament bracket, an
RL population — into ONE dispatch: ``jit(vmap(lax.scan(step)))`` over a
``[M, ...]`` stacked world, with per-lobby inputs and frame counters.

Lobby independence is exact: vmap lanes share machine code, not data, so
lobby b's bits never depend on the other lanes (the same lane-independence
argument as the canonical-branched speculation program, docs/determinism.md)
— proven by the bit-equality test against M separate single-lobby runs
(tests/test_batched_lobbies.py).

Composes with the per-lobby driver loop: each lobby's session/protocol runs
host-side as usual; a server collects each lobby's pending (state, inputs)
work items and flushes them through one batched dispatch instead of M
serial ones (amortizing the per-dispatch submission cost that dominates
small worlds — docs/tpu_notes.md §3b).

Backend note: the win is an ACCELERATOR win (M submissions -> 1, and the
chip is wide enough to eat M small worlds in one pass).  On CPU, measured
8x2000-entity lobbies run ~0.8x the serial rate — XLA:CPU gains nothing
from lane-stacking tiny elementwise work; use per-lobby dispatches there.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..snapshot.world import WorldState
from .resim import resim, resim_padded


def stack_worlds(worlds: List[WorldState]) -> WorldState:
    """Stack M structurally-identical worlds into one [M, ...] pytree."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *worlds)


def unstack_world(batched: WorldState, i: int) -> WorldState:
    """Extract lobby ``i`` from a stacked world (one jitted dispatch)."""
    from ..snapshot.lazy import tree_index

    return tree_index(batched, i)


def make_batched_resim_fn(app):
    """jit(vmap(resim)) over the lobby axis.

    ``fn(batched_world, inputs[M, k, P, ...], status[M, k, P],
    start_frames[M]) -> (finals[M], stacked[M, k], checksums[M, k, 2])`` —
    every lobby advances k frames in one dispatch; per-lobby start frames
    keep independent clocks (lobbies need not be in lockstep).

    Refuses canonical-mode apps: canonical mode exists because the compiled
    program's shape IS a lobby-wide determinism constant for variant-
    unstable float sims (docs/determinism.md), and a vmapped M-lobby program
    is a different program than the single-lobby one the lobby's peers run —
    batching would reintroduce exactly the drift canonical mode removes.
    Integer/fixed-point and variant-stable sims (probe with
    ops/variant_probe.py) batch safely."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode: the "
            "batched program differs from the single-lobby canonical "
            "program every peer dispatches, breaking the one-program "
            "bit-determinism guarantee (see make_batched_resim_fn docstring)"
        )
    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention

    @jax.jit
    def fn(batched_world, inputs_b, status_b, start_frames):
        return jax.vmap(
            lambda w, inp, st, f: resim(
                reg, step, w, inp, st, f, retention, fps, seed
            )
        )(batched_world, inputs_b, status_b, start_frames)

    return fn


def make_batched_padded_fn(app, k_max: int, donate: bool = False):
    """jit(vmap(resim_padded)) over the lobby axis — the BatchedRunner's
    dispatch: every lobby advances up to ``k_max`` frames in ONE call, with
    per-lobby ``n_real`` masking (a lobby with no pending work passes its
    lane through unchanged at ``n_real=0``).

    ``fn(batched_world[M], inputs[M, k_max, P, ...], status[M, k_max, P],
    start_frames[M], n_real[M]) -> (finals[M], stacked[M, k_max],
    checksums_flat[M * k_max, 2])`` — checksums come out pre-flattened so
    one BatchChecks wraps the whole dispatch (row ``b * k_max + i``).

    Same canonical-mode refusal (and rationale) as
    :func:`make_batched_resim_fn`.  ``donate=True`` donates the batched
    world for in-place lane updates (the server's resident-world fast
    path)."""
    if app.canonical_depth is not None or app.canonical_branches is not None:
        raise ValueError(
            "many-worlds batching is incompatible with canonical mode "
            "(see make_batched_resim_fn)"
        )
    reg, step, fps = app.reg, app.step, app.fps
    seed, retention = app.seed, app.retention

    def body(batched_world, inputs_b, status_b, start_frames, n_real):
        finals, stacked, checks = jax.vmap(
            lambda w, inp, st, f, nr: resim_padded(
                reg, step, w, inp, st, f, nr, retention, fps, seed
            )
        )(batched_world, inputs_b, status_b, start_frames, n_real)
        return finals, stacked, checks.reshape(-1, 2)

    return jax.jit(body, donate_argnums=(0,) if donate else ())
