"""Pallas TPU kernel for the per-entity checksum fold.

The checksum hot loop (SURVEY §3.2: O(types x entities) per saved frame) is
a bandwidth-bound integer fold.  XLA already fuses the jnp version well; this
kernel exists to (a) fuse the *whole* per-type pipeline — bitcast, lane fold,
id mix, mask, block-sum — into one VMEM pass with an explicit grid, and
(b) serve as the template for future pallas work (quantized snapshot packing).

Grid: one program per entity block (``block x L`` lanes resident in VMEM);
the sequential TPU grid accumulates partial sums into a single (1, 2) output
block.  Falls back to interpret mode off-TPU, so tests exercise it on CPU;
``use_pallas_checksum(app)`` swaps it into an App.

**Round-3 verdict (real v5e, via tunnel): compiles, bit-exact vs the jnp
path at 10k/100k/1M entities — and does NOT beat XLA** (us/iter, median of
3x50, includes ~2 ms dispatch latency): 10k: 2122 vs 2380 XLA (noise);
100k: 2278 vs 1602; 1M: 14053 vs 2160.  XLA's fusion of the fold into the
surrounding program is already bandwidth-optimal; the hand kernel's narrow
(512, L<=3) blocks underuse the 8x128 VPU lanes.  It is therefore NOT the
default — it stays as the validated pallas template for kernels XLA cannot
fuse (e.g. quantized snapshot bit-packing), with cross-path parity pinned by
tests/test_pallas_hash.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..snapshot.checksum import _type_tag, fmix32, mix32, to_u32_lanes
from ..snapshot.world import Registry, WorldState, active_mask

_BLOCK = 512


def _hash_block_kernel(lanes_ref, ids_ref, mask_ref, out_ref, *, n_lanes, seed_hi, seed_lo):
    """One entity block: fold L lanes per row, mix the stable id, mask, and
    accumulate the block's partial sum for both hash streams.

    All refs are rank-2 (the TPU lowering requires >=2-D block shapes), and
    the output is ONE (1, 2) block shared by every grid step — the TPU grid
    is sequential, so accumulating into it is the canonical pallas reduction
    (wrapping uint32 adds, matching the checksum's reduce semantics)."""
    from jax.experimental import pallas as pl

    lanes = lanes_ref[...]  # [B, L] uint32
    ids = ids_ref[...][:, 0]  # [B, 1] -> [B] uint32
    mask = mask_ref[...][:, 0]  # [B, 1] -> [B] uint32 0/1
    outs = []
    for seed in (seed_hi, seed_lo):
        h = jnp.full(lanes.shape[:1], seed, jnp.uint32)
        for i in range(n_lanes):
            h = mix32(h, lanes[:, i])
        h = fmix32(h ^ jnp.uint32(n_lanes))
        h = fmix32(mix32(h, ids))
        h = jnp.where(mask != 0, h, jnp.uint32(0))
        # Mosaic has no unsigned reduction; int32 wrapping add is
        # bit-identical (two's complement), so the accumulator stays int32
        # in-kernel (scalar bitcast is unsupported) and the caller bitcasts
        # the final (1, 2) block back to uint32
        outs.append(jnp.sum(jax.lax.bitcast_convert_type(h, jnp.int32)))

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros((1, 2), jnp.int32)

    out_ref[...] = out_ref[...] + jnp.stack(outs).reshape(1, 2)


def component_part_pallas(
    reg: Registry, w: WorldState, name: str, seeds, interpret: bool
) -> jnp.ndarray:
    """uint32[2] checksum part for one component via the pallas kernel."""
    from jax.experimental import pallas as pl

    spec = reg.components[name]
    tag_hi = _type_tag(name, seeds[0])
    tag_lo = _type_tag(name, seeds[1])
    col = w.comps[name]
    if spec.hash_fn is not None:
        lanes = spec.hash_fn(col)
        if lanes.ndim == 1:
            lanes = lanes[:, None]
        lanes = lanes.astype(jnp.uint32)
    else:
        lanes = to_u32_lanes(col)
    n, l = lanes.shape
    pad = (-n) % _BLOCK
    if pad:
        lanes = jnp.pad(lanes, ((0, pad), (0, 0)))
    ids = jnp.pad(w.rollback_id.astype(jnp.uint32), (0, pad))
    mask = jnp.pad(
        (active_mask(w) & w.has[name]).astype(jnp.uint32), (0, pad)
    )
    blocks = (n + pad) // _BLOCK

    kernel = functools.partial(
        _hash_block_kernel, n_lanes=l,
        seed_hi=np.uint32(tag_hi), seed_lo=np.uint32(tag_lo),
    )
    partials = pl.pallas_call(
        kernel,
        grid=(blocks,),
        in_specs=[
            pl.BlockSpec((_BLOCK, l), lambda b: (b, 0)),
            pl.BlockSpec((_BLOCK, 1), lambda b: (b, 0)),
            pl.BlockSpec((_BLOCK, 1), lambda b: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, 2), lambda b: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, 2), jnp.int32),
        interpret=interpret,
    )(lanes, ids[:, None], mask[:, None])
    sums = jax.lax.bitcast_convert_type(partials, jnp.uint32)[0]
    return jnp.stack(
        [fmix32(sums[0] ^ jnp.uint32(tag_hi)), fmix32(sums[1] ^ jnp.uint32(tag_lo))]
    )


def world_checksum_pallas(reg: Registry, w: WorldState, interpret: bool = False):
    """Drop-in replacement for snapshot.checksum.world_checksum using the
    pallas block kernel for every checksummed component."""
    from ..snapshot.checksum import _SEED_HI, _SEED_LO, entity_part, resource_part

    hi = entity_part(w, _SEED_HI)
    lo = entity_part(w, _SEED_LO)
    for name, spec in reg.components.items():
        if spec.checksum:
            part = component_part_pallas(reg, w, name, (_SEED_HI, _SEED_LO), interpret)
            hi = hi ^ part[0]
            lo = lo ^ part[1]
    for name, spec in reg.resources.items():
        if spec.checksum:
            hi = hi ^ resource_part(reg, w, name, _SEED_HI)
            lo = lo ^ resource_part(reg, w, name, _SEED_LO)
    return jnp.stack([hi, lo])
