"""Pallas TPU kernel for the per-entity checksum fold.

The checksum hot loop (SURVEY §3.2: O(types x entities) per saved frame) is
a bandwidth-bound integer fold.  XLA already fuses the jnp version well; this
kernel exists to (a) fuse the *whole* per-type pipeline — bitcast, lane fold,
id mix, mask, block-sum — into one VMEM pass with an explicit grid, and
(b) serve as the template for future pallas work (quantized snapshot packing).

Grid: one program per entity block (``block x L`` lanes resident in VMEM);
each program writes one partial uint32 sum per stream; the final (tiny)
reduction happens in jnp.  Falls back to interpret mode off-TPU, so tests
exercise it on CPU; ``use_pallas_checksum(app)`` swaps it into an App.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..snapshot.checksum import _type_tag, fmix32, mix32, to_u32_lanes
from ..snapshot.world import Registry, WorldState, active_mask

_BLOCK = 512


def _hash_block_kernel(lanes_ref, ids_ref, mask_ref, out_ref, *, n_lanes, seed_hi, seed_lo):
    """One entity block: fold L lanes per row, mix the stable id, mask, and
    emit the block's partial sum for both hash streams."""
    lanes = lanes_ref[...]  # [B, L] uint32
    ids = ids_ref[...]  # [B] uint32
    mask = mask_ref[...]  # [B] bool (as uint32 0/1)
    outs = []
    for seed in (seed_hi, seed_lo):
        h = jnp.full(lanes.shape[:1], seed, jnp.uint32)
        for i in range(n_lanes):
            h = mix32(h, lanes[:, i])
        h = fmix32(h ^ jnp.uint32(n_lanes))
        h = fmix32(mix32(h, ids))
        h = jnp.where(mask != 0, h, jnp.uint32(0))
        outs.append(jnp.sum(h, dtype=jnp.uint32))
    out_ref[0] = outs[0]
    out_ref[1] = outs[1]


def component_part_pallas(
    reg: Registry, w: WorldState, name: str, seeds, interpret: bool
) -> jnp.ndarray:
    """uint32[2] checksum part for one component via the pallas kernel."""
    from jax.experimental import pallas as pl

    spec = reg.components[name]
    tag_hi = _type_tag(name, seeds[0])
    tag_lo = _type_tag(name, seeds[1])
    col = w.comps[name]
    if spec.hash_fn is not None:
        lanes = spec.hash_fn(col)
        if lanes.ndim == 1:
            lanes = lanes[:, None]
        lanes = lanes.astype(jnp.uint32)
    else:
        lanes = to_u32_lanes(col)
    n, l = lanes.shape
    pad = (-n) % _BLOCK
    if pad:
        lanes = jnp.pad(lanes, ((0, pad), (0, 0)))
    ids = jnp.pad(w.rollback_id.astype(jnp.uint32), (0, pad))
    mask = jnp.pad(
        (active_mask(w) & w.has[name]).astype(jnp.uint32), (0, pad)
    )
    blocks = (n + pad) // _BLOCK

    kernel = functools.partial(
        _hash_block_kernel, n_lanes=l,
        seed_hi=np.uint32(tag_hi), seed_lo=np.uint32(tag_lo),
    )
    partials = pl.pallas_call(
        kernel,
        grid=(blocks,),
        in_specs=[
            pl.BlockSpec((_BLOCK, l), lambda b: (b, 0)),
            pl.BlockSpec((_BLOCK,), lambda b: (b,)),
            pl.BlockSpec((_BLOCK,), lambda b: (b,)),
        ],
        out_specs=pl.BlockSpec((2,), lambda b: (b,)),
        out_shape=jax.ShapeDtypeStruct((blocks * 2,), jnp.uint32),
        interpret=interpret,
    )(lanes, ids, mask)
    partials = partials.reshape(blocks, 2)
    sums = jnp.sum(partials, axis=0, dtype=jnp.uint32)
    return jnp.stack(
        [fmix32(sums[0] ^ jnp.uint32(tag_hi)), fmix32(sums[1] ^ jnp.uint32(tag_lo))]
    )


def world_checksum_pallas(reg: Registry, w: WorldState, interpret: bool = False):
    """Drop-in replacement for snapshot.checksum.world_checksum using the
    pallas block kernel for every checksummed component."""
    from ..snapshot.checksum import _SEED_HI, _SEED_LO, entity_part, resource_part

    hi = entity_part(w, _SEED_HI)
    lo = entity_part(w, _SEED_LO)
    for name, spec in reg.components.items():
        if spec.checksum:
            part = component_part_pallas(reg, w, name, (_SEED_HI, _SEED_LO), interpret)
            hi = hi ^ part[0]
            lo = lo ^ part[1]
    for name, spec in reg.resources.items():
        if spec.checksum:
            hi = hi ^ resource_part(reg, w, name, _SEED_HI)
            lo = lo ^ resource_part(reg, w, name, _SEED_LO)
    return jnp.stack([hi, lo])
