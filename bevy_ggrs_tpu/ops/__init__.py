from .batch import make_batched_resim_fn, stack_worlds, unstack_world
from .variant_probe import probe_program_variants, VariantProbeReport
from .resim import (
    StepCtx,
    advance,
    resim,
    make_advance_fn,
    make_resim_fn,
    make_speculate_fn,
    select_branch,
    slice_frame,
)

__all__ = [
    "make_batched_resim_fn",
    "stack_worlds",
    "unstack_world",
    "probe_program_variants",
    "VariantProbeReport",
    "StepCtx",
    "advance",
    "resim",
    "make_advance_fn",
    "make_resim_fn",
    "make_speculate_fn",
    "select_branch",
    "slice_frame",
]
