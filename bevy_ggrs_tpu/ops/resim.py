"""Advance / resimulate / speculate — the device-side frame engine.

The reference's request loop runs, per rollback, one LoadWorld then N×
(AdvanceWorld + SaveWorld) as separate host-ECS schedule executions
(/root/reference/src/schedule_systems.rs:189-270; docs/architecture.md:21).
Here that whole batch is ONE compiled call: ``lax.scan`` over the N frames,
emitting every intermediate state (the saves) and checksum as stacked outputs,
so a deep rollback costs one device dispatch instead of N schedule runs.

Speculation goes further than the reference can: ``vmap`` over M predicted
remote-input branches evaluates M alternative futures in parallel; when the
real input arrives, picking the matching branch replaces an entire rollback
resim with a select (the north-star `jit(vmap(lax.scan(step)))` shape).

Frame semantics match the reference: an AdvanceFrame request increments the
frame counter *then* runs the step (schedule_systems.rs:251-268), so the step
computing frame ``f`` sees ``ctx.frame == f`` and GgrsTime ``f / fps``
(src/time.rs:63-87); despawn-retirement sweeps run at the head of every
advance (the DespawnConfirmed pass, src/snapshot/set.rs:69-82) — but at a
FIXED retention horizon ``frame - retention`` instead of the dynamic
confirmed frame: the confirmed frame depends on network timing and differs
across peers, so freeing slots at it would make slot reuse (and thus later
spawns) peer-dependent.  With ``retention >= max_prediction`` the horizon is
always at or before the confirmed frame (the prediction-threshold stall
guarantees ``current - confirmed <= max_prediction``), so retirement stays
rollback-safe AND is a pure function of simulation state.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from ..snapshot.world import Registry, WorldState, despawn_confirmed
from ..snapshot.checksum import world_checksum


@jax.tree_util.register_dataclass
@dataclass
class StepCtx:
    """Per-frame context handed to the user step function.

    ``inputs``/``input_status`` are the ``PlayerInputs`` analog
    (/root/reference/src/lib.rs:92-98); ``time_seconds`` is ``Time<GgrsTime>``
    (frame / fps, src/time.rs:63-87); ``rng_key`` is a per-frame-derived PRNG
    key for convenience (fold of a session seed and the frame — deterministic
    across peers; stateful RNG can instead live in a rollback resource like the
    particles example's Xoshiro, /root/reference/examples/stress_tests/
    particles.rs:125-128)."""

    inputs: jnp.ndarray  # [num_players, *input_shape]
    input_status: jnp.ndarray  # int8[num_players] (InputStatus)
    frame: jnp.ndarray  # int32 scalar — the frame being computed
    retire_frame: jnp.ndarray  # int32 scalar — despawn-retirement horizon
    time_seconds: jnp.ndarray  # f32 scalar — GgrsTime total
    delta_seconds: jnp.ndarray  # f32 scalar — 1 / fps
    rng_key: jnp.ndarray  # jax PRNG key data


StepFn = Callable[[WorldState, StepCtx], WorldState]


def _make_ctx(inputs, status, frame, retire_frame, fps: int, seed: int) -> StepCtx:
    frame = jnp.asarray(frame, jnp.int32)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), frame.astype(jnp.uint32))
    return StepCtx(
        inputs=inputs,
        input_status=status,
        frame=frame,
        retire_frame=jnp.asarray(retire_frame, jnp.int32),
        time_seconds=frame.astype(jnp.float32) / fps,
        delta_seconds=jnp.float32(1.0 / fps),
        rng_key=key,
    )


def advance(
    reg: Registry,
    step_fn: StepFn,
    state: WorldState,
    inputs,
    status,
    frame,
    retention: int,
    fps: int,
    seed: int = 0,
) -> WorldState:
    """One AdvanceWorld: despawn-retirement sweep, then the user step.

    ``retention`` is static (baked into the compile); the sweep frees slots
    whose deferred-despawn frame is <= frame - retention."""
    retire = jnp.asarray(frame, jnp.int32) - jnp.int32(retention)
    state = despawn_confirmed(reg, state, retire)
    ctx = _make_ctx(inputs, status, frame, retire, fps, seed)
    state = step_fn(state, ctx)
    if not reg.is_identity_strategy():
        # lossy snapshot strategies (e.g. QuantizeStrategy) make the STORED
        # representation canonical: round-trip the live state through
        # store->load every frame so a resim from a restored snapshot is
        # bit-identical to the live pass (otherwise SyncTest — and any two
        # peers with different rollback depths — would mismatch by
        # construction).  Fuses into the step program; identity strategies
        # compile to nothing here.
        state = reg.load_state(reg.store_state(state))
    return state


def resim(
    reg: Registry,
    step_fn: StepFn,
    state: WorldState,
    inputs_seq,  # [k, num_players, *input_shape]
    status_seq,  # int8[k, num_players]
    start_frame,  # int32: frame the state currently sits at
    retention: int,
    fps: int,
    seed: int = 0,
    *,
    unroll: int = 1,
    fused_checksums: bool = False,
) -> Tuple[WorldState, WorldState, jnp.ndarray]:
    """Advance ``k`` frames in one fused scan.

    Returns ``(final_state, stacked_states, checksums)`` where
    ``stacked_states`` holds the state *after* each advance (leading axis k —
    the per-frame SaveWorld outputs) and ``checksums`` is uint32[k, 2].

    ``unroll`` forwards to ``lax.scan`` (the default 1 is the program the
    solo runner has always dispatched); ``fused_checksums=False`` likewise
    keeps the historical in-scan checksum placement.  With
    ``fused_checksums=True`` the per-frame checksums are hoisted OUT of the
    scan into one vmapped post-pass over the stacked output — bit-identical
    by construction because :func:`..snapshot.checksum.world_checksum` is a
    uint32 wrapping-add reduction (exactly associative/commutative, no float
    rounding to reassociate), and measurably faster on CPU where the scan
    body is memory-bound.  Batched program builders (ops/batch.py) use both
    knobs; solo paths keep the defaults so recorded sims replay unchanged."""
    start_frame = jnp.asarray(start_frame, jnp.int32)

    def body(carry, x):
        st, f = carry
        inp, stat = x
        nf = f + 1  # AdvanceFrame increments, then steps
        st = advance(reg, step_fn, st, inp, stat, nf, retention, fps, seed)
        out = st if fused_checksums else (st, world_checksum(reg, st))
        return (st, nf), out

    (final, _), outs = jax.lax.scan(
        body, (state, start_frame), (inputs_seq, status_seq), unroll=unroll
    )
    if fused_checksums:
        stacked = outs
        checks = jax.vmap(lambda w: world_checksum(reg, w))(stacked)
    else:
        stacked, checks = outs
    return final, stacked, checks


def resim_padded(
    reg: Registry,
    step_fn: StepFn,
    state: WorldState,
    inputs_seq,  # [k_max, num_players, *input_shape]
    status_seq,  # int8[k_max, num_players]
    start_frame,
    n_real,  # traced scalar: how many leading frames actually advance
    retention: int,
    fps: int,
    seed: int = 0,
    *,
    unroll: int = 1,
    fused_checksums: bool = False,
):
    """Fixed-length scan with masked padding — the bit-determinism program.

    XLA compiles a DIFFERENT program per scan length, and program variants
    may round the same step differently (FMA contraction/fusion differ; a
    measured 56/300 random single-steps mismatched between the k=1 and k=8
    CPU programs).  Peers whose rollback depths differ then drift in low
    float bits and desync.  Running EVERY advance through one fixed-k_max
    program — real frames first, padded frames passing state through
    unchanged — makes the arithmetic identical regardless of segmentation.
    See docs/determinism.md ("One program to advance them all").

    ``unroll``/``fused_checksums`` as in :func:`resim` (defaults reproduce
    the historical program; the hoisted checksum post-pass reads the
    post-``where`` stacked rows, so padded lanes checksum the carried state
    exactly as the in-scan placement did)."""
    start_frame = jnp.asarray(start_frame, jnp.int32)
    n_real = jnp.asarray(n_real, jnp.int32)

    def body(carry, x):
        st, f, i = carry
        inp, stat = x
        nf = f + 1
        st2 = advance(reg, step_fn, st, inp, stat, nf, retention, fps, seed)
        take = i < n_real
        st = jax.tree.map(lambda a, b: jnp.where(take, a, b), st2, st)
        f = jnp.where(take, nf, f)
        out = st if fused_checksums else (st, world_checksum(reg, st))
        return (st, f, i + 1), out

    (final, _, _), outs = jax.lax.scan(
        body, (state, start_frame, jnp.int32(0)), (inputs_seq, status_seq),
        unroll=unroll,
    )
    if fused_checksums:
        stacked = outs
        checks = jax.vmap(lambda w: world_checksum(reg, w))(stacked)
    else:
        stacked, checks = outs
    return final, stacked, checks


def pad_repeat_last(arr, pad: int):
    """Extend the frame axis by repeating the last row ``pad`` times.

    Device arrays are padded with device ops (an async dispatch); host arrays
    with numpy.  Never forces a device->host transfer — calling ``np.asarray``
    on a device array here was the canonical mode's TPU performance bug (one
    flat-latency pull per dispatch; see docs/determinism.md)."""
    if pad == 0:
        return arr
    if isinstance(arr, jax.Array):
        return jnp.concatenate([arr, jnp.repeat(arr[-1:], pad, axis=0)])
    import numpy as np

    arr = np.asarray(arr)
    return np.concatenate([arr, np.repeat(arr[-1:], pad, axis=0)])


_trim_cache = {}


def trim_frames(tree, k: int, axis: int = 0):
    """``tree.map(a[:k])`` (or ``a[:, :k]`` for axis=1) as one jitted
    dispatch (compiled once per (k, axis)) — eager per-leaf slicing costs one
    op submission per leaf."""
    fn = _trim_cache.get((k, axis))
    if fn is None:
        if axis == 0:
            slicer = lambda a: a[:k]
        else:
            slicer = lambda a: a[:, :k]
        fn = _trim_cache[(k, axis)] = jax.jit(
            lambda t: jax.tree.map(slicer, t)
        )
    return fn(tree)


def make_canonical_resim_fn(reg: Registry, step_fn: StepFn, fps: int,
                            seed: int = 0, retention: int = 16,
                            k_max: int = 16, donate: bool = False):
    """jit of :func:`resim_padded` — ONE compiled program for every advance,
    wrapped to the plain resim_fn signature (pads, dispatches, trims).

    ``donate=True`` donates the input state's buffers to XLA (the caller's
    state object is DEAD after the call — the driver only uses this when it
    can prove nothing else aliases the state; see GgrsRunner donation notes).
    Donation lets XLA write the scan carry in place instead of allocating a
    fresh world every dispatch."""

    def body(state, inputs_seq, status_seq, start_frame, n_real):
        return resim_padded(
            reg, step_fn, state, inputs_seq, status_seq, start_frame, n_real,
            retention, fps, seed,
        )

    fn = jax.jit(body, donate_argnums=(0,) if donate else ())

    def wrapped(state, inputs_seq, status_seq, start_frame, _unused=None):
        k = inputs_seq.shape[0]
        if k > k_max:
            raise ValueError(
                f"resim depth {k} exceeds canonical_depth {k_max}; raise "
                "App(canonical_depth=...) above every session window"
            )
        pad = k_max - k
        inputs_seq = pad_repeat_last(inputs_seq, pad)
        status_seq = pad_repeat_last(status_seq, pad)
        final, stacked, checks = fn(state, inputs_seq, status_seq, start_frame, k)
        if pad:
            # one fused dispatch trims both (tuple pytree), not one per leaf
            stacked, checks = trim_frames((stacked, checks), k)
        return final, stacked, checks

    return wrapped


def make_canonical_branched_fn(reg: Registry, step_fn: StepFn, fps: int,
                               seed: int = 0, retention: int = 16,
                               k_max: int = 16, branches: int = 8):
    """ONE fixed [branches, k_max] vmapped program for every dispatch — the
    bit-determinism-safe speculation shape.

    Branch 0 carries the real inputs (its lane is the authoritative result);
    lanes 1.. evaluate hedge candidates in the same dispatch.  vmap lanes are
    independent, so branch 0's arithmetic is one fixed machine code
    regardless of what the other lanes compute — canonical-mode determinism
    AND speculative hedging together (docs/determinism.md)."""

    @jax.jit
    def fn(state, inputs_b, status_b, start_frame, n_real):
        return jax.vmap(
            lambda inp, stat, nr: resim_padded(
                reg, step_fn, state, inp, stat, start_frame, nr,
                retention, fps, seed,
            )
        )(inputs_b, status_b, n_real)

    return fn


def make_advance_fn(reg: Registry, step_fn: StepFn, fps: int, seed: int = 0,
                    retention: int = 16):
    """jit-compiled single-frame advance returning (state, checksum)."""

    @jax.jit
    def fn(state, inputs, status, frame, _retire_unused=None):
        st = advance(reg, step_fn, state, inputs, status, frame, retention, fps, seed)
        return st, world_checksum(reg, st)

    return fn


def make_resim_fn(reg: Registry, step_fn: StepFn, fps: int, seed: int = 0,
                  retention: int = 16, donate: bool = False):
    """jit-compiled k-frame resim (one compile per distinct k).

    ``donate=True`` donates the input state (see
    :func:`make_canonical_resim_fn`): the passed state object is dead after
    the call; XLA may reuse its buffers for the outputs."""

    def body(state, inputs_seq, status_seq, start_frame, _retire_unused=None):
        return resim(
            reg, step_fn, state, inputs_seq, status_seq, start_frame, retention,
            fps, seed
        )

    return jax.jit(body, donate_argnums=(0,) if donate else ())


def make_speculate_fn(reg: Registry, step_fn: StepFn, fps: int, seed: int = 0,
                      retention: int = 16):
    """jit(vmap(scan)) — evaluate M speculative input branches in parallel.

    ``inputs_branches``: [M, k, P, *input_shape]; state is broadcast.  Returns
    (final_states[M], stacked[M, k], checksums[M, k, 2]).  Select the branch
    matching the arrived real inputs with :func:`select_branch`."""

    @jax.jit
    def fn(state, inputs_branches, status_branches, start_frame, _retire_unused=None):
        return jax.vmap(
            lambda inp, stat: resim(
                reg, step_fn, state, inp, stat, start_frame, retention, fps, seed
            )
        )(inputs_branches, status_branches)

    return fn


def make_packed_resim_fn(reg: Registry, step_fn: StepFn, spec, fps: int,
                         seed: int = 0, retention: int = 16,
                         donate: bool = False):
    """jit k-frame resim fed by ONE packed upload (ops/packing.py).

    ``fn(state, packed int8[k+1, W]) -> (final, stacked, checks)`` — the
    single-buffer replacement for :func:`make_resim_fn`'s three uploads
    (inputs, status, start frame).  The in-program split is a pure bitcast,
    so the scan body receives bit-identical arrays and the results match
    the unpacked program's values; one compile per distinct k, as before.

    ``donate=True`` donates the input state (same contract as
    :func:`make_resim_fn`)."""
    from .packing import unpack_seq

    def body(state, packed):
        inputs_seq, status_seq, start_frame, _n, _hl, _ls = unpack_seq(
            spec, packed
        )
        return resim(
            reg, step_fn, state, inputs_seq, status_seq, start_frame,
            retention, fps, seed,
        )

    return jax.jit(body, donate_argnums=(0,) if donate else ())


def make_packed_canonical_resim_fn(reg: Registry, step_fn: StepFn, spec,
                                   fps: int, seed: int = 0,
                                   retention: int = 16, k_max: int = 16):
    """Packed single-upload variant of :func:`make_canonical_resim_fn`.

    ``fn(state, packed int8[k_max+1, W]) -> (final, stacked, checks)`` with
    the real advance count carried in the prefix's ``n_real`` word.  Unlike
    the unpacked wrapper this returns the stacked/checks outputs UNTRIMMED
    at ``k_max`` rows — the caller knows the real row count and indexing
    rows ``< n_real`` is bit-identical to the trimmed view, so skipping the
    trim saves the per-dispatch trim submission.  No donating variant, for
    the same program-variant-drift reason :attr:`App.resim_fn_donated` is
    None in canonical mode."""
    from .packing import unpack_seq

    @jax.jit
    def fn(state, packed):
        inputs_seq, status_seq, start_frame, n_real, _hl, _ls = unpack_seq(
            spec, packed
        )
        return resim_padded(
            reg, step_fn, state, inputs_seq, status_seq, start_frame, n_real,
            retention, fps, seed,
        )

    return fn


def make_packed_speculate_fn(reg: Registry, step_fn: StepFn, spec, fps: int,
                             seed: int = 0, retention: int = 16):
    """Packed single-upload variant of :func:`make_speculate_fn`: the M
    candidate branches ride ONE ``int8[M, depth+1, W]`` buffer (per-branch
    prefix row) instead of three per-dispatch uploads."""
    from .packing import unpack_seq

    @jax.jit
    def fn(state, packed_b):
        def lane(pk):
            inputs_seq, status_seq, start_frame, _n, _hl, _ls = unpack_seq(
                spec, pk
            )
            return resim(
                reg, step_fn, state, inputs_seq, status_seq, start_frame,
                retention, fps, seed,
            )

        return jax.vmap(lane)(packed_b)

    return fn


def select_branch(tree, idx):
    """Pick branch ``idx`` from a leading-axis-M speculation output."""
    return jax.tree.map(lambda a: a[idx], tree)


def slice_frame(stacked_states, i):
    """Extract the state after the (i+1)-th advance from stacked resim output
    (one jitted dispatch — see snapshot/lazy.tree_index)."""
    from ..snapshot.lazy import tree_index

    return tree_index(stacked_states, i)
