"""Lobby quality-of-service scoring: many metric families -> one number.

Dashboards and matchmakers want a single "how healthy is this lobby"
signal, not twelve metric families.  :func:`qos_score` folds the four
dominant degradation axes into one 0..100 gauge:

- **worst-peer ping** — the p95 of ``peer_ping_ms`` for the worst remote
  peer (the slowest link bounds the input-delay budget);
- **rollback rate** — ``rollbacks_total / ticks_total`` (mispredictions
  burn resimulation work and visual stability);
- **forced-readback rate** — ``readback_forced_total`` over all checksum
  readbacks (forced pulls block the host on the device link);
- **tick wall p95** — ``tick_wall_ms`` p95 against the frame budget.

The fold is multiplicative: ``score = 100 * prod(1 / (1 + x_i/scale_i))``,
so the score is **strictly monotone** — worsening any input can only lower
it, improving any input can only raise it (property-tested in
``tests/test_netstats.py``), and a lobby with every axis at its scale
constant lands at ``100 / 2**4``.  No axis can mask another the way a
weighted sum would.

:func:`update_qos_gauges` publishes one ``lobby_qos_score{lobby}`` gauge
per lobby and returns the JSON-able snapshot served by the exporter's
``/qos`` endpoint (:mod:`.prometheus`) and the room server.
"""

from __future__ import annotations

from typing import Dict, Optional

from .metrics import MetricsRegistry, percentile_from_buckets, registry

# Scale constants: the value of each axis that alone halves the score.
PING_SCALE_MS = 120.0  # a transatlantic-grade worst link
ROLLBACK_SCALE = 0.5  # a rollback every other tick
FORCED_SCALE = 0.05  # 5% of checksum readbacks forced (blocking)
TICK_P95_SCALE_MS = 33.3  # two 60fps frame budgets

SCALES = {
    "worst_ping_ms": PING_SCALE_MS,
    "rollback_rate": ROLLBACK_SCALE,
    "forced_readback_rate": FORCED_SCALE,
    "tick_p95_ms": TICK_P95_SCALE_MS,
}


def qos_score(
    worst_ping_ms: float,
    rollback_rate: float,
    forced_readback_rate: float,
    tick_p95_ms: float,
) -> float:
    """Fold the four degradation axes into one 0..100 score.

    Multiplicative and strictly monotone decreasing in every argument
    (negative inputs are clamped to 0 so a bogus sample cannot raise the
    score above the healthy baseline)."""
    score = 100.0
    for value, scale in (
        (worst_ping_ms, PING_SCALE_MS),
        (rollback_rate, ROLLBACK_SCALE),
        (forced_readback_rate, FORCED_SCALE),
        (tick_p95_ms, TICK_P95_SCALE_MS),
    ):
        score *= 1.0 / (1.0 + max(0.0, float(value)) / scale)
    return score


def _counter_total(reg: MetricsRegistry, name: str, lobby=None) -> float:
    """Sum a counter family's series, optionally only those whose ``lobby``
    label matches ``str(lobby)`` (unlabeled series count toward every
    lobby when ``lobby`` is None and toward none otherwise)."""
    total = 0.0
    for m in reg.metrics():
        if m.name != name or m.kind != "counter":
            continue
        for key, val in m.series().items():
            labels = dict(key)
            if lobby is not None and labels.get("lobby") != str(lobby):
                continue
            total += val
    return total


def _histogram_p95_max(reg: MetricsRegistry, name: str) -> float:
    """Worst (max) p95 across every series of histogram family ``name``
    (0.0 when the family is absent or empty)."""
    worst = 0.0
    for m in reg.metrics():
        if m.name != name or m.kind != "histogram":
            continue
        for _key, val in m.series().items():
            p = percentile_from_buckets(m.buckets, val, 0.95)
            if p is not None and p > worst:
                worst = p
    return worst


def _lobby_keys(reg: MetricsRegistry) -> list:
    """Lobby label values seen on ``rollbacks_total`` (the batched driver
    labels per-lobby); ``["default"]`` when none — the solo driver."""
    lobbies = set()
    for m in reg.metrics():
        if m.name != "rollbacks_total":
            continue
        for key, _val in m.series().items():
            lb = dict(key).get("lobby")
            if lb is not None:
                lobbies.add(lb)
    return sorted(lobbies) or ["default"]


def qos_snapshot(reg: Optional[MetricsRegistry] = None) -> dict:
    """Compute the QoS inputs and score for every lobby from the registry.

    Returns the JSON-able ``/qos`` payload::

        {"lobby_qos_score": {lobby: score},
         "lobbies": {lobby: {"score": ..., "inputs": {axis: value}}},
         "scales": {axis: scale}}

    Transport metrics (``peer_ping_ms``) and tick timing are process-wide
    (not lobby-labeled), so they repeat across lobbies; rollback counts use
    the per-lobby series when present."""
    reg = reg or registry()
    worst_ping = _histogram_p95_max(reg, "peer_ping_ms")
    tick_p95 = _histogram_p95_max(reg, "tick_wall_ms")
    ticks = _counter_total(reg, "ticks_total")
    forced = _counter_total(reg, "readback_forced_total")
    harvested = _counter_total(reg, "readback_harvested_total")
    readbacks = forced + harvested
    forced_rate = forced / readbacks if readbacks else 0.0
    lobbies: Dict[str, dict] = {}
    scores: Dict[str, float] = {}
    for lb in _lobby_keys(reg):
        rollbacks = (
            _counter_total(reg, "rollbacks_total")
            if lb == "default"
            else _counter_total(reg, "rollbacks_total", lobby=lb)
        )
        rb_rate = rollbacks / ticks if ticks else 0.0
        inputs = {
            "worst_ping_ms": round(worst_ping, 4),
            "rollback_rate": round(rb_rate, 6),
            "forced_readback_rate": round(forced_rate, 6),
            "tick_p95_ms": round(tick_p95, 4),
        }
        score = round(qos_score(worst_ping, rb_rate, forced_rate, tick_p95), 4)
        lobbies[lb] = {"score": score, "inputs": inputs}
        scores[lb] = score
    return {"lobby_qos_score": scores, "lobbies": lobbies, "scales": dict(SCALES)}


def update_qos_gauges(reg: Optional[MetricsRegistry] = None) -> dict:
    """Publish ``lobby_qos_score{lobby}`` gauges and return the snapshot.

    Gauge writes are no-ops while the registry is disabled; the snapshot is
    computed and returned either way so ``/qos`` always serves data."""
    reg = reg or registry()
    snap = qos_snapshot(reg)
    g = reg.gauge(
        "lobby_qos_score", "folded 0..100 lobby health score (telemetry/qos.py)"
    )
    for lb, score in snap["lobby_qos_score"].items():
        g.set(score, lobby=lb)
    return snap
