"""Chrome Trace Event export — the timeline/flight streams as a Perfetto UI.

The metrics registry aggregates, the timeline orders, the flight recorder
persists — but none of them *draw*.  This module converts both event
streams into Chrome Trace Event Format JSON (the ``{"traceEvents": [...]}``
shape ui.perfetto.dev and chrome://tracing load directly):

- every flight-recorder ``tick`` entry becomes one ``tick`` slice per
  owner/lobby track with one child slice per phase from the
  :data:`~.phases.PHASES` catalog (phase *durations* are exact; their
  order inside the tick is catalog order — the timers accumulate, they
  don't log interleavings);
- ``rollback`` / ``stall`` / ``checksum_mismatch`` / ``desync_report`` /
  ``forced_readback`` / ``spectator_catchup`` / ``input_send`` events
  become instants;
- per-tick counter tracks: ``rollback_depth``, plus
  ``device_resident_bytes`` (:mod:`.devmem`) and ``pipeline_depth`` when
  the driver stamped them into the tick entry;
- **flow arrows**: every ``rollback`` whose blamed ``(handle, to_frame)``
  matches an ``input_send`` event gets a Chrome flow pair (``ph:"s"`` at
  the send, ``ph:"f"`` at the rollback) — "why did frame N roll back" is
  one arrow in the Perfetto UI.  :func:`merge_traces` extends the pairing
  across two peers' traces (clock-aligned on matching tick frames, the
  ``forensics.merge_reports`` alignment idea applied to traces), so the
  arrow crosses from the blamed peer's send track to the victim's rollback.

Consumers: ``--trace-out`` on ``scripts/profile_tick.py`` /
``scripts/replay_tool.py`` / ``bench.py``, the bounded ``/trace`` endpoint
on the Prometheus exporter, and the ``trace_slice`` section of desync
forensics reports.  The event-kind catalog lives in
``docs/observability.md`` "Tracing & device memory" (lint-enforced by
BGT032/BGT033).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

# direct-symbol imports: at package-init time ``telemetry.timeline`` /
# ``telemetry.flight_recorder`` are already rebound to functions, so a
# ``from . import timeline`` here would resolve to the function, not the
# module
from .flight import flight_recorder as _flight_recorder
from .timeline import timeline as _get_timeline

#: timeline kinds converted to instant events (everything else rides args)
_INSTANT_KINDS = (
    "stall", "checksum_mismatch", "desync_report", "spectator_catchup",
    "dispatch", "network_stats", "rollback", "input_send",
    "fleet_wire", "fleet_alert",
)


def _tid_for(ev: dict, tids: Dict[Tuple, int], names: List[dict],
             pid: int) -> int:
    """Stable small-int track id for an event's owner/lobby, registering a
    ``thread_name`` metadata event on first sight."""
    if ev.get("track") is not None:
        # explicit track label: the fleet control plane pins its wire/alert
        # instants to a "scheduler" / "worker:<id>" track
        key = ("track", ev["track"])
        label = str(ev["track"])
    elif ev.get("lobby") is not None:
        key = ("lobby", ev["lobby"])
        label = f"lobby {ev['lobby']}"
    elif ev.get("owner") is not None:
        key = ("owner", ev["owner"])
        label = f"ticks:{ev['owner']}"
    else:
        key = ("main",)
        label = "session"
    tid = tids.get(key)
    if tid is None:
        tid = len(tids)
        tids[key] = tid
        names.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": label},
        })
    return tid


def _counter(out: List[dict], pid: int, ts: float, name: str, value) -> None:
    out.append({"ph": "C", "pid": pid, "name": name, "ts": ts,
                "args": {"value": value}})


def chrome_trace(
    timeline_events: Optional[List[dict]] = None,
    flight_entries: Optional[List[dict]] = None,
    *,
    pid: int = 1,
    process_name: str = "bevy_ggrs_tpu",
    max_events: Optional[int] = None,
    metadata: Optional[dict] = None,
) -> dict:
    """Build a Chrome-trace dict from the two event streams.

    Defaults to the process-wide timeline and flight recorder; pass
    explicit lists to convert a forensics report's sections instead.
    ``max_events`` bounds BOTH sources from the tail (the ``/trace``
    endpoint's cap).  Timestamps are microseconds relative to the earliest
    source event.  Always returns a valid trace — empty sources produce
    ``{"traceEvents": [metadata only], ...}``."""
    default_sources = timeline_events is None and flight_entries is None
    if timeline_events is None:
        timeline_events = _get_timeline().events()
    if flight_entries is None:
        flight_entries = _flight_recorder().snapshot()
    if max_events is not None:
        timeline_events = timeline_events[-max_events:]
        flight_entries = flight_entries[-max_events:]

    ts_all = [e["t"] for e in timeline_events if "t" in e]
    ts_all += [e["t"] for e in flight_entries if "t" in e]
    t0 = min(ts_all) if ts_all else 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    tids: Dict[Tuple, int] = {}
    meta_events: List[dict] = [{
        "ph": "M", "name": "process_name", "pid": pid,
        "args": {"name": process_name},
    }]
    out: List[dict] = []

    # flight tick entries -> tick slice + phase child slices + counters
    for e in flight_entries:
        kind = e.get("kind")
        if kind == "tick":
            tid = _tid_for(e, tids, meta_events, pid)
            wall_us = float(e.get("wall_ms", 0.0)) * 1e3
            end = us(e["t"])
            start = end - wall_us
            args = {k: e[k] for k in
                    ("frame", "rollbacks", "rollback_depth", "advances",
                     "unattributed_ms", "lobbies") if k in e}
            out.append({"ph": "X", "name": "tick", "ts": round(start, 3),
                        "dur": round(wall_us, 3), "pid": pid, "tid": tid,
                        "args": args})
            cursor = start
            for phase, ms in e.get("phases", {}).items():
                dur = min(ms * 1e3, max(end - cursor, 0.0))
                out.append({"ph": "X", "name": phase,
                            "ts": round(cursor, 3), "dur": round(dur, 3),
                            "pid": pid, "tid": tid, "args": {}})
                cursor += dur
            _counter(out, pid, end, "rollback_depth",
                     e.get("rollback_depth", 0))
            if "device_bytes" in e:
                _counter(out, pid, end, "device_resident_bytes",
                         e["device_bytes"])
            if "pipeline_depth" in e:
                _counter(out, pid, end, "pipeline_depth",
                         e["pipeline_depth"])
        elif kind in ("compile", "forced_readback"):
            tid = _tid_for(e, tids, meta_events, pid)
            args = {k: v for k, v in e.items()
                    if k not in ("seq", "t", "kind", "owner", "lobby")}
            out.append({"ph": "i", "s": "t", "name": kind, "ts": us(e["t"]),
                        "pid": pid, "tid": tid, "args": args})

    # timeline events -> instants (+ "span" slices from the legacy sink)
    have_tl_rollbacks = any(
        e.get("kind") == "rollback" for e in timeline_events
    )
    for e in timeline_events:
        kind = e.get("kind")
        if kind == "span" and "t0" in e:
            tid = _tid_for({"owner": "spans"}, tids, meta_events, pid)
            out.append({"ph": "X", "name": e.get("name", "span"),
                        "ts": us(e["t0"]), "dur": round(e.get("ms", 0) * 1e3, 3),
                        "pid": pid, "tid": tid, "args": {}})
        elif kind in _INSTANT_KINDS:
            tid = _tid_for(e, tids, meta_events, pid)
            args = {k: v for k, v in e.items()
                    if k not in ("seq", "t", "kind", "lobby", "track")}
            out.append({"ph": "i", "s": "t", "name": kind, "ts": us(e["t"]),
                        "pid": pid, "tid": tid, "args": args})
    if not have_tl_rollbacks:
        # telemetry was off: the always-on flight ring still has the
        # attributed rollback entries — surface them so flows can anchor
        for e in flight_entries:
            if e.get("kind") == "rollback":
                tid = _tid_for(e, tids, meta_events, pid)
                args = {k: v for k, v in e.items()
                        if k not in ("seq", "t", "kind", "owner", "lobby")}
                out.append({"ph": "i", "s": "t", "name": "rollback",
                            "ts": us(e["t"]), "pid": pid, "tid": tid,
                            "args": args})

    out.sort(key=lambda ev: ev["ts"])
    events = meta_events + out
    events.extend(_flow_events(events))

    md = {
        "clock": "perf_counter_us",
        "t0_seconds": t0,
        "timeline_events_dropped": (
            _get_timeline().dropped if default_sources else None
        ),
        "flight_record_evictions": (
            _flight_recorder().evictions if default_sources else None
        ),
    }
    if metadata:
        md.update(metadata)
    return {"traceEvents": events, "displayTimeUnit": "ms", "metadata": md}


def _flow_events(events: List[dict],
                 require_cross_pid: bool = False,
                 start_id: int = 1) -> List[dict]:
    """Chrome flow pairs linking each ``rollback`` instant to the
    ``input_send`` instant that caused it.

    A rollback blames ``(handle, to_frame)``; the matching send is the one
    whose sender owns that handle (``handle in args["handles"]``) for that
    frame.  With ``require_cross_pid`` (the merged-trace case) only sends
    from the OTHER peer qualify — a peer never blames its own handle, but
    two merged in-process traces could otherwise double-match."""
    sends = [e for e in events
             if e.get("ph") == "i" and e.get("name") == "input_send"]
    flows: List[dict] = []
    fid = start_id
    for rb in events:
        if rb.get("ph") != "i" or rb.get("name") != "rollback":
            continue
        args = rb.get("args", {})
        handle, frame = args.get("handle"), args.get("to_frame")
        if handle is None or frame is None:
            continue
        for send in sends:
            sa = send.get("args", {})
            if sa.get("frame") != frame or handle not in sa.get("handles", ()):
                continue
            if require_cross_pid and send.get("pid") == rb.get("pid"):
                continue
            common = {"cat": "input_flow", "name": "late_input", "id": fid}
            flows.append({"ph": "s", "ts": send["ts"], "pid": send["pid"],
                          "tid": send["tid"], **common})
            flows.append({"ph": "f", "bp": "e", "ts": rb["ts"],
                          "pid": rb["pid"], "tid": rb["tid"], **common})
            sa["flow_id"] = fid
            args["flow_id"] = fid
            fid += 1
            break
    return flows


def flows(trace: dict) -> List[dict]:
    """The trace's resolved flow arrows as ``{"id", "send", "rollback"}``
    arg dicts — what the flow-correlation tests assert on."""
    by_id: Dict[int, dict] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "i":
            continue
        fid = e.get("args", {}).get("flow_id")
        if fid is None:
            continue
        side = "send" if e.get("name") == "input_send" else "rollback"
        by_id.setdefault(fid, {"id": fid})[side] = e.get("args", {})
    return [v for _, v in sorted(by_id.items())
            if "send" in v and "rollback" in v]


def write_trace(path: str, **kw) -> int:
    """Serialize :func:`chrome_trace` to ``path``; returns the event count."""
    trace = chrome_trace(**kw)
    with open(path, "w") as f:
        json.dump(trace, f, default=repr)
    return len(trace["traceEvents"])


def trace_from_report(report: dict, *, pid: int = 1,
                      process_name: Optional[str] = None) -> dict:
    """Convert one desync forensics report's ``timeline_tail`` +
    ``flight_record`` sections into a Chrome trace (per-peer input to
    :func:`merge_traces`)."""
    return chrome_trace(
        report.get("timeline_tail") or [],
        report.get("flight_record") or [],
        pid=pid,
        process_name=process_name or f"peer:{report.get('addr') or pid}",
        metadata={"report_kind": report.get("kind"),
                  "timeline_events_dropped": None,
                  "flight_record_evictions": None},
    )


#: (scheduler send op, worker completion op, flow label): the fleet wire
#: pairs the merged view links with flow arrows.  The CKPT -> RESUME_OK
#: "migration" arrow spans exactly the measured migration downtime —
#: barrier-checkpoint-in-hand to restored-on-destination.
_FLEET_FLOW_PAIRS = (
    ("CKPT", "RESUME_OK", "migration"),
    ("PLACE", "PLACE_OK", "place"),
    ("DRAIN", "DRAINED", "drain"),
)

#: worker completion op -> the scheduler send op it answers (clock
#: alignment bounds for traces that share no tick frames)
_WIRE_RESP = {
    "PLACE_OK": "PLACE", "DRAINED": "DRAIN",
    "RESUME_OK": "RESUME", "DROP_RECV": "DROP",
}


def _tick_ts(evs: List[dict]) -> Dict[int, float]:
    """frame -> tick-slice ts (the cross-peer alignment anchors)."""
    return {e["args"]["frame"]: e["ts"] for e in evs
            if e.get("ph") == "X" and e.get("name") == "tick"
            and e.get("args", {}).get("frame") is not None}


def _wire_ts(evs: List[dict]) -> Dict[Tuple, List[float]]:
    """(lid, op) -> sorted ``fleet_wire`` instant timestamps."""
    d: Dict[Tuple, List[float]] = {}
    for e in evs:
        if e.get("ph") != "i" or e.get("name") != "fleet_wire":
            continue
        a = e.get("args", {})
        d.setdefault((a.get("lid"), a.get("op")), []).append(e["ts"])
    return {k: sorted(v) for k, v in d.items()}


def _wire_offset(base: List[dict], new: List[dict]) -> Optional[float]:
    """Clock offset (added to ``new``'s ts) from matched fleet wire
    send/completion pairs — the alignment fallback when the traces share
    no tick frames (a scheduler trace has no tick slices at all).

    A completion happens after its send in real time, so every matched
    pair bounds the offset from one side: a send in ``base`` answered in
    ``new`` gives a lower bound, the mirrored direction an upper bound.
    Taking the tightest bounds makes the estimation error the smallest
    send->completion processing delay among the matched pairs (the DROP ->
    DROP_RECV pair is usually one poll quantum)."""
    ca, cb = _wire_ts(base), _wire_ts(new)
    lowers: List[float] = []  # off >= ts_send(base) - ts_completion(new)
    uppers: List[float] = []  # off <= ts_completion(base) - ts_send(new)
    for (lid, resp_op), resp_ts in cb.items():
        send_ts = ca.get((lid, _WIRE_RESP.get(resp_op)))
        if send_ts:
            lowers.extend(s - r for s, r in zip(send_ts, resp_ts))
    for (lid, resp_op), resp_ts in ca.items():
        send_ts = cb.get((lid, _WIRE_RESP.get(resp_op)))
        if send_ts:
            uppers.extend(r - s for s, r in zip(send_ts, resp_ts))
    if lowers and uppers:
        return (max(lowers) + min(uppers)) / 2.0
    if lowers:
        return max(lowers)
    if uppers:
        return min(uppers)
    return None


def _fleet_flow_events(events: List[dict], start_id: int = 1) -> List[dict]:
    """Cross-pid flow pairs linking scheduler ``fleet_wire`` commands to
    the worker-side completions (:data:`_FLEET_FLOW_PAIRS`), matched by
    lobby id in timestamp order.  Stamps ``flow_id`` into both instants'
    args like :func:`_flow_events` does for input flows."""
    wires = [e for e in events
             if e.get("ph") == "i" and e.get("name") == "fleet_wire"]
    flows: List[dict] = []
    fid = start_id
    for src_op, dst_op, label in _FLEET_FLOW_PAIRS:
        srcs = sorted((e for e in wires
                       if e.get("args", {}).get("op") == src_op),
                      key=lambda e: e["ts"])
        dsts = sorted((e for e in wires
                       if e.get("args", {}).get("op") == dst_op),
                      key=lambda e: e["ts"])
        used = set()
        for s in srcs:
            lid = s.get("args", {}).get("lid")
            for j, d in enumerate(dsts):
                if j in used or d.get("args", {}).get("lid") != lid:
                    continue
                if d.get("pid") == s.get("pid") or d["ts"] < s["ts"]:
                    continue
                common = {"cat": "fleet_flow", "name": label, "id": fid}
                flows.append({"ph": "s", "ts": s["ts"], "pid": s["pid"],
                              "tid": s["tid"], **common})
                flows.append({"ph": "f", "bp": "e", "ts": d["ts"],
                              "pid": d["pid"], "tid": d["tid"], **common})
                s["args"]["flow_id"] = fid
                d["args"]["flow_id"] = fid
                used.add(j)
                fid += 1
                break
    return flows


def merge_traces(trace_a: dict, trace_b: dict, *more: dict) -> dict:
    """Merge N participants' traces into one, clock-aligned and
    flow-correlated (two-peer calls behave exactly as before).

    The FIRST trace is the clock reference; every other trace is shifted
    onto it — for a fleet merge pass the scheduler first, then the
    workers.  Alignment per trace: the median offset over tick slices for
    common frames when the pair shares any (the two-peer desync-forensics
    case), else matched ``fleet_wire`` send/completion pairs
    (:func:`_wire_offset` — workers share wire events with the scheduler,
    never tick frames).  Pids are shifted on collision so each participant
    keeps its own process lane.

    After alignment two flow families are re-paired cross-pid: rollback ->
    ``input_send`` blame arrows (:func:`_flow_events`) and scheduler ->
    worker fleet wire arrows (:func:`_fleet_flow_events`) — the
    ``migration`` arrow spans the measured downtime gap end-to-end."""
    traces = [trace_a, trace_b, *more]
    parts = [[dict(e) for e in t.get("traceEvents", [])] for t in traces]
    for evs in parts:
        for e in evs:
            # drop stale in-process flow stamps: the merged view re-pairs
            # cross-pid only, and flows() must not see the old ids
            a = e.get("args")
            if a and "flow_id" in a:
                e["args"] = {k: v for k, v in a.items() if k != "flow_id"}
    base = parts[0]
    used_pids = {e.get("pid") for e in base if e.get("pid") is not None}
    aligned = 0
    for evs in parts[1:]:
        pids = {e.get("pid") for e in evs if e.get("pid") is not None}
        if pids & used_pids:
            shift = max(used_pids, default=0) + 1
            for e in evs:
                if e.get("pid") is not None:
                    e["pid"] = e["pid"] + shift
            pids = {p + shift for p in pids}
        used_pids |= pids
        ta, tb = _tick_ts(base), _tick_ts(evs)
        common = sorted(set(ta) & set(tb))
        if common:
            offsets = sorted(ta[f] - tb[f] for f in common)
            off = offsets[len(offsets) // 2]
            aligned += len(common)
        else:
            off = _wire_offset(base, evs)
        if off is not None:
            for e in evs:
                if "ts" in e:
                    e["ts"] = round(e["ts"] + off, 3)
    merged = [e for evs in parts for e in evs
              if e.get("ph") != "s" and e.get("ph") != "f"]
    merged.sort(key=lambda ev: (ev.get("ph") != "M", ev.get("ts", 0.0)))
    input_flows = _flow_events(merged, require_cross_pid=True)
    fleet_flows = _fleet_flow_events(
        merged, start_id=1 + len(input_flows) // 2
    )
    merged.extend(input_flows)
    merged.extend(fleet_flows)
    metas = [t.get("metadata", {}) for t in traces]
    md = {
        "merged": True,
        "participants": len(traces),
        "aligned_frames": aligned,
        "a": metas[0],
        "b": metas[1],
        "parts": metas,
    }
    return {"traceEvents": merged, "displayTimeUnit": "ms", "metadata": md}


def merge_report_traces(report_a: dict, report_b: dict) -> dict:
    """Two desync reports -> one merged, flow-correlated Chrome trace
    (the ``replay_tool.py merge-reports --trace-out`` payload)."""
    return merge_traces(
        trace_from_report(report_a, pid=1),
        trace_from_report(report_b, pid=2),
    )


_REQUIRED = {
    "X": ("ts", "dur", "pid", "tid", "name"),
    "i": ("ts", "pid", "tid", "name"),
    "C": ("ts", "pid", "name", "args"),
    "M": ("pid", "name", "args"),
    "s": ("ts", "pid", "tid", "id"),
    "f": ("ts", "pid", "tid", "id"),
}


def validate_chrome_trace(trace) -> List[str]:
    """Structural well-formedness check (the bench smoke gate): required
    keys per event phase, non-negative durations, ``ts`` monotonic per
    ``(pid, tid)`` track for complete events, and every flow id present as
    a start/finish pair.  Returns a list of problems (empty = valid)."""
    problems: List[str] = []
    if not isinstance(trace, dict) or not isinstance(
        trace.get("traceEvents"), list
    ):
        return ["top level must be a dict with a traceEvents list"]
    last_ts: Dict[Tuple, float] = {}
    flow_phs: Dict[int, set] = {}
    for i, e in enumerate(trace["traceEvents"]):
        if not isinstance(e, dict) or "ph" not in e:
            problems.append(f"event {i}: not a dict with ph")
            continue
        ph = e["ph"]
        for key in _REQUIRED.get(ph, ()):
            if key not in e:
                problems.append(f"event {i} (ph={ph}): missing {key}")
        if ph == "X":
            if e.get("dur", 0) < 0:
                problems.append(f"event {i}: negative dur")
            track = (e.get("pid"), e.get("tid"))
            ts = e.get("ts", 0.0)
            if ts < last_ts.get(track, float("-inf")):
                problems.append(
                    f"event {i}: ts {ts} not monotonic on track {track}"
                )
            last_ts[track] = ts
        elif ph in ("s", "f"):
            flow_phs.setdefault(e.get("id"), set()).add(ph)
    for fid, phs in flow_phs.items():
        if phs != {"s", "f"}:
            problems.append(f"flow id {fid}: unpaired ({sorted(phs)})")
    return problems
