"""Unified telemetry subsystem: metrics registry, per-frame rollback
timeline, and desync forensics export.

The reference plugin leans on Bevy's tracing backend for observability; our
seed had a span ring plus ad-hoc counters scattered across three layers.
This package is the single replacement surface:

- :mod:`.metrics` — process-local registry of counters / gauges / labeled
  histograms (``rollback_depth``, ``resim_frames_total``,
  ``speculation_hit_ratio``, ``checksum_mismatch_total``, ...).
- :mod:`.timeline` — one ordered event stream per process merging the span
  ring, per-peer network stats and driver decisions; JSONL export.
- :mod:`.forensics` — per-component checksum reports on desync, plus the
  cross-peer ``merge_reports`` alignment.
- :mod:`.netstats` — periodic per-peer NetworkStats/TimeSync sampler
  (``peer_ping_ms``, ``frame_advantage``, ...; ``BGT_NETSTATS_EVERY``).
- :mod:`.qos` — lobby health scoring (``lobby_qos_score``, the ``/qos``
  endpoint payload).
- :mod:`.prometheus` — HTTP ``/metrics`` + ``/qos`` exporter (room server).

Everything is DISABLED by default and near-free while disabled; flip it on
with :func:`enable` (or ``BGT_TELEMETRY=1`` in the environment).  Metric
catalog and usage live in ``docs/observability.md``.
"""

from __future__ import annotations

import os

from . import devmem  # noqa: F401 (namespace re-export: telemetry.devmem)
from .flight import (  # noqa: F401 (public re-exports)
    FlightRecorder,
    configure as configure_flight,
    dump_flight_record,
    flight_recorder,
)
from .forensics import (  # noqa: F401
    component_checksums,
    configure as configure_forensics,
    forensics_dir,
    merge_reports,
    write_desync_report,
)
from .metrics import (  # noqa: F401
    FRAME_BUCKETS,
    LATENCY_MS_BUCKETS,
    MS_BUCKETS,
    BoundMetric,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    percentile_from_buckets,
    registry,
)
from .phases import (  # noqa: F401
    PHASES,
    PhaseSet,
    format_phase_table,
    phase_breakdown,
)
from .netstats import NetStatsSampler  # noqa: F401
from .prometheus import MetricsExporter, start_http_exporter  # noqa: F401
from .qos import qos_score, qos_snapshot, update_qos_gauges  # noqa: F401
from .timeline import (  # noqa: F401
    Timeline,
    export_jsonl,
    record,
    span_sink,
    timeline,
)
from .trace import (  # noqa: F401
    chrome_trace,
    flows,
    merge_report_traces,
    merge_traces,
    trace_from_report,
    validate_chrome_trace,
    write_trace,
)

__all__ = [
    "BoundMetric",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "MetricsExporter",
    "Timeline", "FRAME_BUCKETS", "MS_BUCKETS", "LATENCY_MS_BUCKETS",
    "PHASES", "PhaseSet", "FlightRecorder",
    "phase_breakdown", "format_phase_table",
    "enable", "disable", "enabled", "reset", "summary",
    "registry", "timeline", "record", "export_jsonl", "span_sink",
    "count", "observe", "gauge_set", "percentile_from_buckets",
    "component_checksums", "configure_forensics", "forensics_dir",
    "write_desync_report", "merge_reports", "start_http_exporter",
    "flight_recorder", "configure_flight", "dump_flight_record",
    "NetStatsSampler", "qos_score", "qos_snapshot", "update_qos_gauges",
    "devmem", "chrome_trace", "write_trace", "validate_chrome_trace",
    "trace_from_report", "merge_traces", "merge_report_traces", "flows",
]


def enabled() -> bool:
    """True when telemetry recording is on."""
    return registry().enabled


def enable() -> None:
    """Turn on metrics + timeline recording and hook the span ring in."""
    registry().set_enabled(True)
    from ..utils import tracing

    tracing.set_span_sink(span_sink())


def disable() -> None:
    """Turn recording back off (recorded data stays until :func:`reset`)."""
    registry().set_enabled(False)
    from ..utils import tracing

    tracing.set_span_sink(None)


def reset() -> None:
    """Drop all recorded metrics, timeline events, flight-recorder entries
    and device-memory accounting rows (test isolation)."""
    registry().reset()
    timeline().clear()
    flight_recorder().clear()
    devmem.reset()


def count(name: str, n: float = 1, help: str = "", **labels) -> None:
    """Increment counter ``name`` on the default registry (shorthand)."""
    reg = registry()
    if reg.enabled:
        reg.counter(name, help).inc(n, **labels)


def observe(name: str, v: float, help: str = "", buckets=FRAME_BUCKETS, **labels) -> None:
    """Observe ``v`` on histogram ``name`` on the default registry."""
    reg = registry()
    if reg.enabled:
        reg.histogram(name, help, buckets=buckets).observe(v, **labels)


def gauge_set(name: str, v: float, help: str = "", **labels) -> None:
    """Set gauge ``name`` on the default registry."""
    reg = registry()
    if reg.enabled:
        reg.gauge(name, help).set(v, **labels)


def _latency_percentiles(reg) -> dict:
    """p50/p95/p99 per series of the tick-latency histogram families
    (``tick_phase_ms`` / ``tick_wall_ms`` / ``tick_unattributed_ms``),
    estimated from their cumulative log-spaced buckets.  Keys are the
    series label strings (e.g. ``owner=solo,phase=wave_dispatch``)."""
    out = {}
    for m in reg.metrics():
        if m.kind != "histogram" or m.name not in (
            "tick_phase_ms", "tick_wall_ms", "tick_unattributed_ms",
            "program_compile_ms",
        ):
            continue
        fam = {}
        for key, series in m.series().items():
            skey = ",".join(f"{k}={v}" for k, v in key)
            fam[skey] = {
                f"p{q * 100:g}": round(
                    percentile_from_buckets(m.buckets, series, q), 4
                )
                for q in (0.5, 0.95, 0.99)
            }
            fam[skey]["count"] = series["count"]
        if fam:
            out[m.name] = fam
    return out


def summary() -> dict:
    """One merged dict of everything: the ``bench.py`` BENCH payload.

    Includes derived ratios (``speculation_hit_ratio``) and per-phase
    latency percentiles (``latency_ms`` — p50/p95/p99 per
    ``tick_phase_ms`` series) computed from the raw metrics so consumers
    need no metric arithmetic."""
    reg = registry()
    snap = reg.snapshot()

    def _total(name: str) -> float:
        fam = snap.get(name)
        if not fam:
            return 0.0
        return float(sum(v if not isinstance(v, dict) else v.get("count", 0)
                         for v in fam["series"].values()))

    hits = _total("speculation_hits_total")
    misses = _total("speculation_misses_total")
    return {
        "enabled": reg.enabled,
        "metrics": snap,
        "derived": {
            "speculation_hit_ratio": (
                round(hits / (hits + misses), 4) if hits + misses else None
            ),
            "rollbacks_total": _total("rollbacks_total"),
            "resim_frames_total": _total("resim_frames_total"),
            "checksum_mismatch_total": _total("checksum_mismatch_total"),
            "readback_harvested_total": _total("readback_harvested_total"),
            "readback_forced_total": _total("readback_forced_total"),
            "host_blocked_seconds": _total("host_blocked_seconds"),
            "pipeline_degrade_total": _total("pipeline_degrade_total"),
            "latency_ms": _latency_percentiles(reg),
        },
        "timeline_events": len(timeline()),
        "timeline_events_dropped": timeline().dropped,
        "flight_record_entries": len(flight_recorder()),
        "flight_record_evictions": flight_recorder().evictions,
        # live device-memory residency (always-on registry — see
        # telemetry/devmem.py; owner catalog in docs/observability.md)
        "device_resident_bytes": devmem.snapshot(),
        "device_resident_total_bytes": devmem.total(),
    }


if os.environ.get("BGT_TELEMETRY", "").strip() in ("1", "true", "on", "yes"):
    enable()
