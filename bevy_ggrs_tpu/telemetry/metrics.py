"""Process-local metrics registry — counters, gauges, histograms with labels.

The unified stat mechanism replacing the ad-hoc integer attributes scattered
across ``runner.py`` / ``batch_runner.py`` / ``session/p2p.py``: every driver
and session counter routes through one :class:`MetricsRegistry` so a single
``snapshot()`` (or Prometheus scrape — see :mod:`.prometheus`) answers "why
did this lobby stall / desync / roll back 7 frames".

Cost model: the registry is DISABLED by default.  Every mutating call
(``inc``/``set``/``observe``) returns after one attribute check when
disabled, so instrumented hot paths (the per-tick driver loop) pay a few ns
per site — the <2% bench budget in ISSUE.md.  Enable with
:func:`bevy_ggrs_tpu.telemetry.enable` (or ``BGT_TELEMETRY=1``).

Label semantics follow Prometheus: a metric name owns a family of time
series keyed by sorted ``(label, value)`` pairs.  Histograms use fixed
upper-bound buckets (cumulative on export, like Prometheus ``le``).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

# default histogram buckets, tuned for the two native unit families:
# frames (rollback depth, input latency — small ints) and milliseconds
FRAME_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 32.0)
MS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0)
# fixed log-spaced latency buckets (1-2-5 per decade, 5us .. 1s) — the
# tick-phase timers' family: wide enough that one set covers a sub-ms CPU
# staging phase and a 100ms+ cold-compile dispatch without re-bucketing
LATENCY_MS_BUCKETS = (
    0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5,
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0, 1000.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    """Common base: name, help text, per-label-set series storage."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str = ""):
        self._reg = registry
        self.name = name
        self.help = help
        self._series: Dict[LabelKey, object] = {}

    def series(self) -> Dict[LabelKey, object]:
        """Raw per-label-set values (shallow copy, lock-protected)."""
        with self._reg._lock:
            return dict(self._series)


class Counter(_Metric):
    """Monotonically increasing value (e.g. ``rollbacks_total``)."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        """Add ``n`` (default 1) to the series selected by ``labels``."""
        if not self._reg.enabled:
            return
        key = _label_key(labels)
        with self._reg._lock:
            self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> float:
        """Current value of one series (0 if never incremented)."""
        return self._series.get(_label_key(labels), 0)


class Gauge(_Metric):
    """Point-in-time value that can go up or down (e.g. ``ping_ms``)."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        """Set the series selected by ``labels`` to ``v``."""
        if not self._reg.enabled:
            return
        with self._reg._lock:
            self._series[_label_key(labels)] = v

    def set_key(self, key: LabelKey, v: float) -> None:
        """Set by precomputed label key — hot-path variant (the
        ``Histogram.observe_key`` analog) for callers that cache the key."""
        if not self._reg.enabled:
            return
        with self._reg._lock:
            self._series[key] = v

    def inc(self, n: float = 1, **labels) -> None:
        """Add ``n`` to the gauge (down with negative ``n``)."""
        if not self._reg.enabled:
            return
        key = _label_key(labels)
        with self._reg._lock:
            self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> float:
        """Current value of one series (0 if never set)."""
        return self._series.get(_label_key(labels), 0)


class Histogram(_Metric):
    """Fixed-bucket distribution (e.g. ``rollback_depth`` in frames).

    Each series stores per-bucket counts plus ``sum``/``count``; export
    renders cumulative Prometheus ``le`` buckets."""

    kind = "histogram"

    def __init__(self, registry, name, help="", buckets: Sequence[float] = FRAME_BUCKETS):
        super().__init__(registry, name, help)
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))

    def observe(self, v: float, **labels) -> None:
        """Record one observation of ``v``."""
        if not self._reg.enabled:
            return
        self.observe_key(_label_key(labels), v)

    def observe_key(self, key: LabelKey, v: float) -> None:
        """Observe with a pre-resolved label key — the hot-path variant:
        callers that observe the same series every tick (the phase timers)
        build the key once instead of sorting a label dict per call."""
        if not self._reg.enabled:
            return
        with self._reg._lock:
            s = self._series.get(key)
            if s is None:
                s = {"buckets": [0] * len(self.buckets), "sum": 0.0, "count": 0}
                self._series[key] = s
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    s["buckets"][i] += 1
                    break
            s["sum"] += v
            s["count"] += 1

    def snapshot(self, **labels) -> Optional[dict]:
        """One series as ``{"buckets", "sum", "count"}`` (or None)."""
        s = self._series.get(_label_key(labels))
        if s is None:
            return None
        return {"buckets": list(s["buckets"]), "sum": s["sum"], "count": s["count"]}

    def percentile(self, q: float, **labels) -> Optional[float]:
        """Estimate the ``q``-quantile (0 < q <= 1) of one series from its
        cumulative bucket counts — linear interpolation inside the covering
        bucket (the ``histogram_quantile`` estimator).  Observations past the
        last finite bound clamp to it, exactly like Prometheus; returns None
        for an empty/absent series."""
        s = self.snapshot(**labels)
        return percentile_from_buckets(self.buckets, s, q) if s else None

    def percentiles(self, qs=(0.5, 0.95, 0.99), **labels) -> Optional[dict]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` for one series (one
        snapshot, N estimates), or None for an empty/absent series."""
        s = self.snapshot(**labels)
        if not s or not s["count"]:
            return None
        return {
            f"p{q * 100:g}": percentile_from_buckets(self.buckets, s, q)
            for q in qs
        }


class MetricsRegistry:
    """Get-or-create metric families; snapshot/export the lot.

    One instance per process is the intended shape (:func:`registry`); tests
    may build private registries.  ``enabled`` gates every mutation — flip it
    with :meth:`set_enabled` (the package-level ``enable()``/``disable()``
    forward here)."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()
        # bumped on every reset() so BoundMetric handles held by hot loops
        # know their cached family object is stale
        self.generation = 0

    def set_enabled(self, enabled: bool) -> None:
        """Enable/disable all mutation on this registry's metrics."""
        self.enabled = bool(enabled)

    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(self, name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        """Get or create a :class:`Counter` named ``name``."""
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        """Get or create a :class:`Gauge` named ``name``."""
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "", buckets: Sequence[float] = FRAME_BUCKETS
    ) -> Histogram:
        """Get or create a :class:`Histogram` named ``name``."""
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def metrics(self) -> List[_Metric]:
        """All registered metric families, name-sorted."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """Plain-dict dump: ``{name: {"kind", "help", "series": {...}}}``.

        Series keys are rendered as ``label=value,label=value`` strings
        ("" for the unlabeled series) so the result is JSON-serializable —
        this is the dict ``bench.py`` merges into BENCH output."""
        out = {}
        for m in self.metrics():
            series = {}
            for key, val in m.series().items():
                skey = ",".join(f"{k}={v}" for k, v in key)
                if isinstance(val, dict):  # histogram series
                    series[skey] = {
                        "sum": val["sum"],
                        "count": val["count"],
                        "buckets": dict(
                            zip([str(b) for b in m.buckets], val["buckets"])
                        ),
                    }
                else:
                    series[skey] = val
            out[m.name] = {"kind": m.kind, "help": m.help, "series": series}
        return out

    def reset(self) -> None:
        """Drop every metric family (test isolation).

        Bumps :attr:`generation` so :class:`BoundMetric` handles held by hot
        loops re-resolve their family on the next call instead of mutating an
        orphaned object."""
        with self._lock:
            self._metrics.clear()
            self.generation += 1

    def bind_counter(self, name: str, help: str = "") -> "BoundMetric":
        """Pre-bound counter handle for hot loops (see :class:`BoundMetric`)."""
        return BoundMetric(self, "counter", name, help)

    def bind_gauge(self, name: str, help: str = "") -> "BoundMetric":
        """Pre-bound gauge handle for hot loops."""
        return BoundMetric(self, "gauge", name, help)

    def bind_histogram(
        self, name: str, help: str = "", buckets: Sequence[float] = FRAME_BUCKETS
    ) -> "BoundMetric":
        """Pre-bound histogram handle for hot loops."""
        return BoundMetric(self, "histogram", name, help, buckets=buckets)

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4) of everything."""
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, val in sorted(m.series().items()):
                if isinstance(val, dict):  # histogram
                    cum = 0
                    for ub, n in zip(m.buckets, val["buckets"]):
                        cum += n
                        lines.append(
                            f"{m.name}_bucket{_fmt_labels(key, le=_fmt_float(ub))} {cum}"
                        )
                    lines.append(
                        f'{m.name}_bucket{_fmt_labels(key, le="+Inf")} {val["count"]}'
                    )
                    lines.append(f"{m.name}_sum{_fmt_labels(key)} {_fmt_float(val['sum'])}")
                    lines.append(f"{m.name}_count{_fmt_labels(key)} {val['count']}")
                else:
                    lines.append(f"{m.name}{_fmt_labels(key)} {_fmt_float(val)}")
        return "\n".join(lines) + "\n"


def percentile_from_buckets(buckets, series: dict, q: float) -> Optional[float]:
    """The quantile estimator shared by :meth:`Histogram.percentile` and
    offline consumers (``telemetry.summary()``, ``--phase-breakdown``):
    walk the fixed ``buckets`` against one series' per-bucket counts, then
    interpolate linearly inside the bucket covering rank ``q * count``.
    Observations above the last finite bound clamp to it (the Prometheus
    ``histogram_quantile`` convention)."""
    count = series.get("count", 0)
    if not count:
        return None
    target = q * count
    cum = 0
    lo = 0.0
    for ub, n in zip(buckets, series["buckets"]):
        if n:
            if cum + n >= target:
                return lo + (ub - lo) * (target - cum) / n
            cum += n
        lo = ub
    return float(buckets[-1])  # overflow (+Inf) bucket: clamp


def _fmt_float(v) -> str:
    """Render a number the way Prometheus text format expects."""
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return str(v)


def _escape_label_value(v: str) -> str:
    """Label-value escaping per text format 0.0.4: backslash, double-quote
    and line feed must be escaped or a scrape with e.g. a peer address of
    ``"\\n"`` in a label silently corrupts the whole exposition."""
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping per text format 0.0.4 (backslash and line feed)."""
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_labels(key: LabelKey, **extra) -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in key] + [
        f'{k}="{_escape_label_value(str(v))}"' for k, v in extra.items()
    ]
    return "{" + ",".join(parts) + "}" if parts else ""


class BoundMetric:
    """Resolve-once handle to a metric family for per-tick hot paths.

    The ad-hoc ``telemetry.count(name, n, help=...)`` convenience re-passes the
    name and help string on every call, which in the driver loop means a dict
    lookup plus string traffic per tick per metric.  A ``BoundMetric`` does the
    name/help registration exactly once (at construction) and afterwards its
    :meth:`inc`/:meth:`set`/:meth:`observe` are a couple of attribute checks
    plus the underlying metric mutation.  The handle watches the registry's
    ``generation`` counter so a ``reset()`` (test isolation) transparently
    re-creates the family rather than mutating an orphan that no snapshot
    will ever see.
    """

    __slots__ = ("_reg", "_kind", "_name", "_help", "_kw", "_gen", "_m")

    def __init__(self, reg: MetricsRegistry, kind: str, name: str, help: str, **kw):
        self._reg = reg
        self._kind = kind
        self._name = name
        self._help = help
        self._kw = kw
        self._gen = -1
        self._m: Optional[_Metric] = None
        self._resolve()

    def _resolve(self) -> _Metric:
        if self._kind == "counter":
            self._m = self._reg.counter(self._name, self._help)
        elif self._kind == "gauge":
            self._m = self._reg.gauge(self._name, self._help)
        else:
            self._m = self._reg.histogram(self._name, self._help, **self._kw)
        self._gen = self._reg.generation
        return self._m

    def _metric(self) -> _Metric:
        m = self._m
        if self._gen != self._reg.generation:
            m = self._resolve()
        return m

    def inc(self, n: float = 1) -> None:
        """Counter/gauge increment by ``n`` (no labels — that's the point)."""
        if not self._reg.enabled:
            return
        self._metric().inc(n)

    def set(self, v: float) -> None:
        """Gauge set."""
        if not self._reg.enabled:
            return
        self._metric().set(v)

    def observe(self, v: float) -> None:
        """Histogram observation."""
        if not self._reg.enabled:
            return
        self._metric().observe(v)

    def value(self) -> float:
        """Current unlabeled value (0 if the family was reset away)."""
        return self._metric().value()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry."""
    return _REGISTRY
