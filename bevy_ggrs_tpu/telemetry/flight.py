"""Always-on flight recorder — the last-N-ticks black box.

The metrics registry answers "how often / how much"; the timeline answers
"in what order" — but both are OFF by default, so a production stall or
desync that happens with telemetry disabled leaves nothing to read.  The
flight recorder closes that gap the way an aircraft FDR does: a small
fixed-size ring of the most recent ticks' **phase breakdowns** (per-phase
milliseconds from :mod:`.phases`, wall tick time, the unattributed
residual) plus the driver's frame/rollback decisions and forced-readback
stalls, recorded ALWAYS (unless explicitly disabled) at a cost of one dict
build + deque append per recorded tick.

Consumed two ways:

- dumped into every desync forensics report (:mod:`.forensics`) so the
  report shows what the driver was doing in the ticks leading up to the
  divergence, and
- on demand via :func:`bevy_ggrs_tpu.telemetry.dump_flight_record` (or the
  ``--phase-breakdown`` flag on ``scripts/profile_tick.py`` /
  ``scripts/replay_tool.py``, which computes exact per-phase percentiles
  from the ring).

Disable with ``BGT_FLIGHT_RECORD=0`` (or ``configure(enabled=False)``) to
shave the last microsecond off the disabled-telemetry tick path.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Deque, List, Optional

_DEFAULT_MAXLEN = 256


class FlightRecorder:
    """Bounded ring of recent driver events (see module docstring).

    Entries are plain JSON-serializable dicts stamped with a monotonic
    ``seq`` and ``t`` (``perf_counter`` seconds); the ring drops the oldest
    entry past ``maxlen``.  Appends are GIL-atomic (deque), so recording
    from a driver thread while another thread snapshots is safe."""

    def __init__(self, maxlen: int = _DEFAULT_MAXLEN, enabled: bool = True):
        self.enabled = bool(enabled)
        self._ring: Deque[dict] = deque(maxlen=int(maxlen))
        self._seq = 0
        self.evictions = 0  # entries pushed out past the ring bound

    @property
    def maxlen(self) -> int:
        """The ring bound (entries kept)."""
        return self._ring.maxlen or 0

    def set_maxlen(self, maxlen: int) -> None:
        """Resize the ring, keeping the newest entries that still fit
        (entries shed by a shrink count as :attr:`evictions`)."""
        maxlen = int(maxlen)
        if maxlen != self._ring.maxlen:
            self.evictions += max(len(self._ring) - maxlen, 0)
            self._ring = deque(self._ring, maxlen=maxlen)

    def record(self, kind: str, **fields) -> None:
        """Append one event (``kind`` ∈ ``tick`` / ``rollback`` /
        ``compile`` / ``forced_readback`` / ...); no-op when disabled.

        Appending past the ring bound evicts the oldest entry and counts it
        in :attr:`evictions` (surfaced by ``telemetry.summary()`` and trace
        metadata) — a bounded black box must say what it forgot."""
        if not self.enabled:
            return
        self._seq += 1
        ev = {"seq": self._seq, "t": time.perf_counter(), "kind": kind}
        ev.update(fields)
        ring = self._ring
        if len(ring) == ring.maxlen:
            self.evictions += 1
        ring.append(ev)

    def snapshot(self, kind: Optional[str] = None) -> List[dict]:
        """The ring's entries in order (optionally one ``kind`` only)."""
        evs = list(self._ring)
        if kind is not None:
            evs = [ev for ev in evs if ev.get("kind") == kind]
        return evs

    def clear(self) -> None:
        """Drop every entry and reset :attr:`evictions` (the sequence
        counter keeps counting)."""
        self._ring.clear()
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._ring)

    def dump(self, path: str) -> int:
        """Write the ring as one JSON document; returns the entry count."""
        evs = self.snapshot()
        with open(path, "w") as f:
            json.dump(
                {"ts": time.time(), "maxlen": self.maxlen, "events": evs},
                f, indent=2, default=repr,
            )
        return len(evs)


_FLIGHT = FlightRecorder(
    enabled=os.environ.get("BGT_FLIGHT_RECORD", "").strip()
    not in ("0", "false", "off", "no"),
)


def flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder."""
    return _FLIGHT


def configure(
    maxlen: Optional[int] = None, enabled: Optional[bool] = None
) -> FlightRecorder:
    """Adjust the process recorder's ring size and/or on/off switch."""
    if maxlen is not None:
        _FLIGHT.set_maxlen(maxlen)
    if enabled is not None:
        _FLIGHT.enabled = bool(enabled)
    return _FLIGHT


def dump_flight_record(path: str) -> int:
    """Dump the process flight recorder to ``path`` (JSON); entry count."""
    return _FLIGHT.dump(path)
