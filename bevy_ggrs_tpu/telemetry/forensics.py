"""Desync forensics — per-component checksum dumps on checksum mismatch.

A 64-bit world checksum says two peers diverged; it cannot say WHERE.  This
module decomposes the divergence: on a SyncTest mismatch or a P2P
``DesyncDetected`` event the driver calls :func:`write_desync_report`, which
hashes every registered component/resource SEPARATELY (the same per-type
parts ``snapshot/checksum.py`` XORs into the world checksum), attaches the
last N timeline events plus the full metrics snapshot, and writes one JSON
report file.  Diffing two peers' reports names the diverged component
directly — the workflow is documented in ``docs/debugging-desyncs.md`` §6.

Reports are written only when a directory is configured
(:func:`configure` or ``BGT_FORENSICS_DIR``); the hooks are otherwise free.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from . import flight as _flight
from . import metrics as _metrics
from . import timeline as _timeline

_STATE = {
    "dir": os.environ.get("BGT_FORENSICS_DIR") or None,
    "timeline_tail": 200,
}


def configure(dir: Optional[str] = None, timeline_tail: Optional[int] = None) -> None:
    """Set the report directory (None disables) and timeline excerpt length."""
    _STATE["dir"] = dir
    if timeline_tail is not None:
        _STATE["timeline_tail"] = int(timeline_tail)


def forensics_dir() -> Optional[str]:
    """The configured report directory, or None when reporting is off."""
    return _STATE["dir"]


def component_checksums(reg, world) -> dict:
    """Per-part 64-bit checksums of ``world``: one per checksummed component
    and resource, plus the entity part — all pulled in ONE device transfer.

    Keys are component names, ``res:<name>`` for resources and
    ``__entities__``; values are ints comparable across peers exactly like
    the combined world checksum (uint32 math — see snapshot/checksum.py)."""
    import jax

    from ..snapshot.checksum import (
        _SEED_HI,
        _SEED_LO,
        component_part,
        entity_part,
        resource_part,
    )

    parts = {}
    for name, spec in reg.components.items():
        if spec.checksum:
            parts[name] = [
                component_part(reg, world, name, _SEED_HI),
                component_part(reg, world, name, _SEED_LO),
            ]
    for name, spec in reg.resources.items():
        if spec.checksum:
            parts["res:" + name] = [
                resource_part(reg, world, name, _SEED_HI),
                resource_part(reg, world, name, _SEED_LO),
            ]
    parts["__entities__"] = [entity_part(world, _SEED_HI), entity_part(world, _SEED_LO)]
    # bgt: ignore[BGT011]: forensics runs only AFTER a detected desync — the
    # sim is already divergent, so forcing the per-component readback here is
    # deliberate and can never stall a healthy tick
    host = jax.device_get(parts)
    return {
        name: (int(hi) << 32) | int(lo) for name, (hi, lo) in host.items()
    }


def write_desync_report(
    kind: str,
    reg=None,
    world=None,
    frames=None,
    local_checksum: Optional[int] = None,
    remote_checksum: Optional[int] = None,
    addr=None,
    lobby: Optional[int] = None,
    path: Optional[str] = None,
    checksums: Optional[dict] = None,
) -> Optional[str]:
    """Dump a desync forensics report; returns the file path (or None when
    no directory is configured and no explicit ``path`` given).

    ``kind`` is ``"synctest_mismatch"`` or ``"p2p_desync"``; ``reg``/``world``
    (when available) produce the per-component checksum section.
    ``checksums`` is the per-frame ``{frame: world_checksum}`` map the
    session still holds — the alignment key :func:`merge_reports` uses to
    find the first divergent frame across two peers' reports."""
    if path is None:
        d = _STATE["dir"]
        if d is None:
            return None
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"desync_{kind}_{int(time.time() * 1e3)}_{os.getpid()}.json"
        )
    report = {
        "kind": kind,
        "ts": time.time(),
        "frames": list(frames) if frames is not None else None,
        "local_checksum": local_checksum,
        "remote_checksum": remote_checksum,
        "addr": repr(addr) if addr is not None else None,
        "lobby": lobby,
        "checksums": (
            {int(f): v for f, v in checksums.items()}
            if checksums is not None
            else None
        ),
        "component_checksums": (
            component_checksums(reg, world)
            if reg is not None and world is not None
            else None
        ),
        "timeline_tail": _timeline.timeline().tail(_STATE["timeline_tail"]),
        # always-on black box: the last-N-ticks phase breakdowns and
        # rollback decisions are present even when telemetry was never
        # enabled (docs/observability.md "Flight recorder")
        "flight_record": _flight.flight_recorder().snapshot(),
        "metrics": _metrics.registry().snapshot(),
    }
    # Perfetto-loadable excerpt of the same window: extract with jq
    # '.trace_slice' or feed both peers' reports to replay_tool.py
    # merge-reports --trace-out for the cross-peer flow-arrow view
    from .trace import chrome_trace

    report["trace_slice"] = chrome_trace(
        report["timeline_tail"], report["flight_record"],
        metadata={"report_kind": kind},
    )
    with open(path, "w") as f:
        json.dump(report, f, indent=2, default=repr)
    reg_ = _metrics.registry()
    if reg_.enabled:
        reg_.counter(
            "desync_reports_total", "forensics reports written"
        ).inc(kind=kind)
    _timeline.record("desync_report", report_kind=kind, path=path)
    return path


def _frame_checksums(report: dict) -> dict:
    """The report's per-frame checksum map with int frame keys (JSON
    round-trips dict keys as strings)."""
    out = {}
    for k, v in (report.get("checksums") or {}).items():
        try:
            out[int(k)] = v
        except (TypeError, ValueError):
            continue
    return out


def _flight_entries(report: dict, kind: str) -> list:
    """Entries of one kind from the report's flight-record section."""
    return [
        e
        for e in (report.get("flight_record") or [])
        if isinstance(e, dict) and e.get("kind") == kind
    ]


def merge_reports(path_a: str, path_b: str) -> dict:
    """Cross-peer forensics merge: align two peers' desync reports by frame
    and localize the divergence (``replay_tool.py merge-reports``).

    Frame-aligns both reports' per-frame checksum maps, finds the first
    frame where both peers recorded a value and the values differ, diffs the
    per-component checksum sections, and pulls each side's flight-recorder
    context (tick entries around the divergent frame, every rollback
    decision with its blamed handle).  Returns::

        {"first_divergent_frame": int | None,
         "common_frames": n, "divergent_frames": [f, ...],
         "checksums_at_divergence": {"a": ..., "b": ...},
         "component_diff": [name, ...] | None,
         "rollbacks": {"a": [...], "b": [...]},
         "tick_context": {"a": [...], "b": [...]}}

    ``first_divergent_frame`` is None when the overlapping frames agree —
    the divergence happened outside the retained checksum window (rerun
    with a denser desync-detection interval; see
    ``docs/debugging-desyncs.md`` §0)."""
    with open(path_a) as f:
        a = json.load(f)
    with open(path_b) as f:
        b = json.load(f)
    cs_a, cs_b = _frame_checksums(a), _frame_checksums(b)
    common = sorted(set(cs_a) & set(cs_b))
    divergent = [f for f in common if cs_a[f] != cs_b[f]]
    first = divergent[0] if divergent else None
    if first is None:
        # no overlapping per-frame data disagreed; fall back to the frames
        # the detectors themselves flagged (present in both reports)
        flagged = sorted(
            set(a.get("frames") or []) & set(b.get("frames") or [])
        )
        first = flagged[0] if flagged else None
    comp_diff = None
    ca, cb = a.get("component_checksums"), b.get("component_checksums")
    if ca and cb:
        comp_diff = sorted(
            name
            for name in set(ca) | set(cb)
            if ca.get(name) != cb.get(name)
        )

    def _context(rep: dict) -> list:
        if first is None:
            return _flight_entries(rep, "tick")[-8:]
        return [
            e
            for e in _flight_entries(rep, "tick")
            if e.get("frame") is not None and abs(e["frame"] - first) <= 4
        ]

    return {
        "a": path_a,
        "b": path_b,
        "first_divergent_frame": first,
        "common_frames": len(common),
        "divergent_frames": divergent,
        "checksums_at_divergence": (
            {"a": cs_a.get(first), "b": cs_b.get(first)}
            if first is not None
            else None
        ),
        "component_diff": comp_diff,
        "rollbacks": {
            "a": _flight_entries(a, "rollback"),
            "b": _flight_entries(b, "rollback"),
        },
        "tick_context": {"a": _context(a), "b": _context(b)},
    }
