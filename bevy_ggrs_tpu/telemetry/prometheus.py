"""Prometheus HTTP exporter — serve the registry on ``GET /metrics``.

A tiny stdlib ``ThreadingHTTPServer`` wrapper so any long-lived process
(``scripts/room_server.py`` is the shipped consumer) can expose the metrics
registry to a Prometheus scraper with one call:

    from bevy_ggrs_tpu.telemetry import start_http_exporter
    exporter = start_http_exporter(port=9464)
    ...
    exporter.close()

The handler renders :meth:`MetricsRegistry.render_prometheus` per scrape —
no caching, no extra thread work between scrapes.  ``GET /qos`` serves the
JSON lobby-health snapshot from :mod:`.qos` (schema documented in
``docs/observability.md``), refreshing the ``lobby_qos_score`` gauges as a
side effect so the next ``/metrics`` scrape carries them too.
``GET /trace`` serves a bounded Chrome-trace JSON snapshot of the process
timeline + flight recorder (:mod:`.trace`) — save it and drop it straight
into ui.perfetto.dev (``?n=`` caps the per-stream event count, default
``TRACE_DEFAULT_EVENTS``)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from .metrics import MetricsRegistry, registry as _default_registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
QOS_CONTENT_TYPE = "application/json; charset=utf-8"

# /trace response bound: events taken from the tail of EACH source stream
# (timeline + flight ring); a scraper polling a busy server must never pull
# an unbounded 64Ki-event body
TRACE_DEFAULT_EVENTS = 2048
TRACE_MAX_EVENTS = 16384


class MetricsExporter:
    """Background HTTP server exposing one registry (see module docstring)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 extra_json_routes: Optional[
                     Dict[str, Callable[[], dict]]] = None):
        reg = registry if registry is not None else _default_registry()
        # path -> zero-arg callable returning a JSON-able payload; checked
        # BEFORE the builtin paths so a caller can override them (the fleet
        # exporter replaces /qos with the fleet-wide worst-N view and adds
        # /fleet — fleet/observe.py).  Callables run on handler threads and
        # must be thread-safe.
        extra = dict(extra_json_routes or {})

        class Handler(BaseHTTPRequestHandler):
            """Per-scrape request handler (``/metrics`` + ``/`` index)."""

            def do_GET(self):  # noqa: N802 (stdlib naming)
                """Serve exposition text (``/metrics``) or QoS JSON (``/qos``)."""
                path, _, query = self.path.partition("?")
                if path in extra:
                    body = json.dumps(
                        extra[path](), default=repr
                    ).encode("utf-8")
                    ctype = QOS_CONTENT_TYPE
                elif path == "/qos":
                    from .qos import update_qos_gauges

                    body = json.dumps(update_qos_gauges(reg)).encode("utf-8")
                    ctype = QOS_CONTENT_TYPE
                elif path == "/trace":
                    from .trace import chrome_trace

                    n = TRACE_DEFAULT_EVENTS
                    for part in query.split("&"):
                        if part.startswith("n="):
                            try:
                                n = int(part[2:])
                            except ValueError:
                                pass
                    n = max(1, min(n, TRACE_MAX_EVENTS))
                    body = json.dumps(
                        chrome_trace(max_events=n), default=repr
                    ).encode("utf-8")
                    ctype = QOS_CONTENT_TYPE
                elif path in ("/metrics", "/"):
                    body = reg.render_prometheus().encode("utf-8")
                    ctype = CONTENT_TYPE
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                """Silence per-request stderr logging."""

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="ggrs-metrics-exporter",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        """The bound port (useful with ``port=0``)."""
        return self._server.server_address[1]

    def close(self) -> None:
        """Stop serving and release the socket."""
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


def start_http_exporter(port: int = 0, host: str = "127.0.0.1",
                        registry: Optional[MetricsRegistry] = None,
                        extra_json_routes: Optional[
                            Dict[str, Callable[[], dict]]] = None,
                        ) -> MetricsExporter:
    """Start a :class:`MetricsExporter`; returns it (``.port``, ``.close()``)."""
    return MetricsExporter(port=port, host=host, registry=registry,
                           extra_json_routes=extra_json_routes)
