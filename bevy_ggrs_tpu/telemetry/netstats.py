"""Per-peer network-health sampler: NetworkStats + TimeSync -> metrics.

The driver polls the session's transport every host tick, but scraping
``network_stats()`` for every remote handle at tick rate would cost more
than the data is worth — ping and bandwidth move on quality-report
timescales (hundreds of milliseconds), not frame timescales.  The
:class:`NetStatsSampler` snapshots every remote peer once per ``every``
driver polls (default 60 — once a second at 60 fps) into these families:

- ``peer_ping_ms{handle}`` — round-trip ping histogram
  (``LATENCY_MS_BUCKETS``, so ``percentile_from_buckets`` works on it);
- ``peer_send_queue{handle}`` — pending outbound input packets;
- ``peer_kbps{handle}`` — outbound bandwidth to the peer;
- ``peer_frames_behind{handle,side=local|remote}`` — both sides' frame lag;
- ``frame_advantage{handle}`` — the smoothed per-endpoint
  :meth:`TimeSync.frames_ahead` estimate driving run-slow;
- ``time_sync_warmup{handle}`` — 1 while the peer's TimeSync has not seen
  both sides' advantage data (``frames_ahead`` is one-sided until then);
- ``netstats_samples_total`` — sweeps performed (cadence sanity check).

Cost discipline: ``poll()`` is ONE attribute load + boolean check when the
sampler is disabled (``BGT_NETSTATS_EVERY=0``), an integer increment and
compare between samples, and only touches the registry on the 1-in-``every``
sampling tick — and then only while telemetry is enabled.  Handles whose
:class:`NetworkStats` report ``is_live=False`` (local players, spectators,
disconnected peers) are skipped silently: no logs, no zero-valued series.

Catalog and environment knobs are documented in
``docs/observability.md`` ("Network & QoS").
"""

from __future__ import annotations

import os

from .metrics import LATENCY_MS_BUCKETS, registry

DEFAULT_EVERY = 60  # driver polls between sweeps (~1 s at 60 fps)
ENV_EVERY = "BGT_NETSTATS_EVERY"


def _every_from_env(default: int = DEFAULT_EVERY) -> int:
    """Resolve the sampling cadence from ``BGT_NETSTATS_EVERY``.

    Unset/unparsable values fall back to ``default``; ``0`` (or any
    non-positive value) disables the sampler entirely."""
    raw = os.environ.get(ENV_EVERY, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


class NetStatsSampler:
    """Periodic per-peer NetworkStats/TimeSync sweep (see module docstring).

    Attached by the driver's ``set_session`` to any session exposing
    ``network_stats``; ``poll()`` is called once per host tick inside the
    ``net_poll`` phase."""

    def __init__(self, session, every: int | None = None):
        self.session = session
        self.every = _every_from_env() if every is None else int(every)
        self.enabled = self.every > 0
        self._n = 0
        self.samples = 0

    def poll(self) -> None:
        """Count one driver poll; sweep every ``every``-th call.

        The disabled path is a single boolean check — keep it that way
        (the <1% hot-loop budget of docs/observability.md)."""
        if not self.enabled:
            return
        self._n += 1
        if self._n < self.every:
            return
        self._n = 0
        if registry().enabled:
            self.sample()

    def _handles(self):
        """Remote player handles of the attached session (empty when the
        session exposes neither the explicit surface nor the addr map)."""
        fn = getattr(self.session, "remote_player_handles", None)
        if fn is not None:
            return fn()
        addr_map = getattr(self.session, "remote_handle_addr", None)
        return sorted(addr_map) if addr_map else []

    def sample(self) -> None:
        """One sweep: snapshot every live remote handle into the per-peer
        metric families.  Non-live handles (``is_live=False``) are skipped
        silently; sessions without per-endpoint TimeSync fall back to the
        session-wide ``frames_ahead`` estimate."""
        s = self.session
        reg = registry()
        ping_h = reg.histogram(
            "peer_ping_ms", "round-trip ping per remote peer",
            buckets=LATENCY_MS_BUCKETS,
        )
        q_g = reg.gauge("peer_send_queue", "pending outbound inputs per peer")
        kbps_g = reg.gauge("peer_kbps", "outbound bandwidth per peer")
        behind_g = reg.gauge(
            "peer_frames_behind",
            "frame lag per peer and side (side=local|remote)",
        )
        adv_g = reg.gauge(
            "frame_advantage",
            "smoothed frames-ahead estimate per peer (run-slow driver)",
        )
        warm_g = reg.gauge(
            "time_sync_warmup",
            "1 while the peer's TimeSync lacks two-sided data",
        )
        time_sync_for = getattr(s, "time_sync_for", None)
        frames_ahead = getattr(s, "frames_ahead", None)
        swept = 0
        for h in self._handles():
            st = s.network_stats(h)
            if not st.is_live:
                continue
            swept += 1
            ping_h.observe(st.ping_ms, handle=h)
            q_g.set(st.send_queue_len, handle=h)
            kbps_g.set(st.kbps_sent, handle=h)
            behind_g.set(st.local_frames_behind, handle=h, side="local")
            behind_g.set(st.remote_frames_behind, handle=h, side="remote")
            ts = time_sync_for(h) if time_sync_for is not None else None
            if ts is not None:
                adv_g.set(ts.frames_ahead(), handle=h)
                warm_g.set(0 if ts.warmed_up() else 1, handle=h)
            elif frames_ahead is not None:
                adv_g.set(frames_ahead(), handle=h)
                warm_g.set(0, handle=h)
        if swept:
            self.samples += 1
            reg.counter(
                "netstats_samples_total", "per-peer NetworkStats sweeps"
            ).inc()
