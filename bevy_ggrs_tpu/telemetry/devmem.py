"""Device-memory accounting — who owns the bytes resident on the device.

The drivers keep several long-lived device (and pinned-host staging)
allocations alive between ticks: the snapshot ring, the megastep device
ring, the packed/unpacked staging buffers, the batched resident worlds and
the speculation branch cache.  None of them show up in any metric, so "why
is HBM full" has meant reading allocation sites.  This module is the
registry that answers it:

- every long-lived allocation site calls :func:`note` with an **owner**
  string and its current byte count (absolute, not a delta — re-noting
  after a reallocation or a ring push replaces the old figure);
- owners are namespaced per driver instance via :func:`scope`
  (``solo0/snapshot_ring``, ``batched0/worlds``, ...) and garbage-collected
  with the instance via :func:`forget_scope` (the drivers register a
  ``weakref.finalize``), so a long bench run never accumulates stale rows;
- while telemetry is enabled every note also lands on the
  ``device_resident_bytes{owner}`` gauge (docs/observability.md "Tracing &
  device memory"); the plain-dict registry itself is ALWAYS on — one dict
  store per note — so :func:`snapshot` works even when metrics never were;
- :func:`census` reconciles the registry against ``jax.live_arrays()`` —
  registered-but-freed or live-but-unregistered bytes are the drift the
  reconciliation test bounds.

``telemetry.summary()`` carries :func:`snapshot` + :func:`total` as the
live-residency line, and the Chrome-trace export (:mod:`.trace`) emits
:func:`total` as a counter track per tick.
"""

from __future__ import annotations

from typing import Dict

from . import metrics as _metrics

_BUFFERS: Dict[str, int] = {}
_SCOPE_COUNTS: Dict[str, int] = {}

_GAUGE_HELP = (
    "bytes of long-lived device/staging memory per owning allocation site"
)

# generation-checked gauge-family + label-key cache (the BoundMetric idiom):
# note() runs inside drivers' per-tick ring/staging updates, so it must not
# re-pay the family lookup and label-tuple build on every call.
_gauge_gen = -1
_gauge = None
_owner_keys: Dict[str, tuple] = {}


def _gauge_key(reg, owner: str):
    global _gauge_gen, _gauge
    if _gauge_gen != reg.generation:
        _gauge = reg.gauge("device_resident_bytes", _GAUGE_HELP)
        _owner_keys.clear()
        _gauge_gen = reg.generation
    key = _owner_keys.get(owner)
    if key is None:
        key = _owner_keys[owner] = _metrics._label_key({"owner": owner})
    return _gauge, key


def scope(prefix: str) -> str:
    """A unique owner namespace for one driver instance (``solo0``,
    ``solo1``, ...).  Pair with ``weakref.finalize(self, forget_scope, tag)``
    so the rows die with the instance."""
    n = _SCOPE_COUNTS.get(prefix, 0)
    _SCOPE_COUNTS[prefix] = n + 1
    return f"{prefix}{n}"


def note(owner: str, nbytes: int) -> None:
    """Record ``owner``'s current resident byte count (absolute).

    Always updates the registry dict; mirrors to the
    ``device_resident_bytes`` gauge only while telemetry is enabled, so a
    note from a hot path costs one dict store when telemetry is off."""
    nbytes = int(nbytes)
    _BUFFERS[owner] = nbytes
    reg = _metrics.registry()
    if reg.enabled:
        gauge, key = _gauge_key(reg, owner)
        gauge.set_key(key, nbytes)


def forget(owner: str) -> None:
    """Drop one owner's row (its buffers were freed); zeroes the gauge."""
    _BUFFERS.pop(owner, None)
    reg = _metrics.registry()
    if reg.enabled:
        gauge, key = _gauge_key(reg, owner)
        gauge.set_key(key, 0)


def forget_scope(tag: str) -> None:
    """Drop every owner under ``tag/`` — the driver-finalizer cleanup."""
    for owner in [o for o in _BUFFERS if o == tag or o.startswith(tag + "/")]:
        forget(owner)


def snapshot() -> Dict[str, int]:
    """``{owner: bytes}`` — the current registry contents."""
    return dict(_BUFFERS)


def total() -> int:
    """Sum over all owners (the trace export's counter-track value)."""
    return sum(_BUFFERS.values())


def reset() -> None:
    """Drop every row and scope counter (test isolation; wired into
    ``telemetry.reset()``)."""
    _BUFFERS.clear()
    _SCOPE_COUNTS.clear()


def census(strict: bool = False) -> dict:
    """Reconcile the registry against ``jax.live_arrays()``.

    Returns ``{"registered_bytes", "live_bytes", "live_arrays",
    "unregistered_bytes", "owners"}``.  ``live_bytes`` counts every live
    jax array in the process — including transients in flight — so
    ``unregistered_bytes`` (live minus registered, floored at 0) is an
    upper bound on what the owners table is missing, not an exact leak.
    ``live_bytes`` is None when the running jax has no ``live_arrays``.

    ``strict=True`` additionally asserts the registry is not STALE: every
    registered byte must be backed by a live array, so
    ``registered_bytes > live_bytes`` proves some owner dropped device
    buffers without re-noting (the SpeculationCache ``invalidate_after``/
    ``_trim`` class of bug) and raises ``RuntimeError`` naming the owners."""
    live_bytes = None
    n_live = None
    try:
        import jax

        arrays = jax.live_arrays()
        n_live = len(arrays)
        live_bytes = 0
        for a in arrays:
            try:
                live_bytes += int(a.size) * a.dtype.itemsize
            except (AttributeError, TypeError):
                continue
    except (ImportError, AttributeError, RuntimeError):
        pass
    registered = total()
    if strict and live_bytes is not None and registered > live_bytes:
        owners = ", ".join(
            f"{k}={v}" for k, v in sorted(_BUFFERS.items()) if v > 0
        )
        raise RuntimeError(
            f"devmem registry is stale: registered_bytes={registered} > "
            f"live_bytes={live_bytes} — an owner dropped device buffers "
            f"without re-noting (owners: {owners})"
        )
    return {
        "registered_bytes": registered,
        "live_bytes": live_bytes,
        "live_arrays": n_live,
        "unregistered_bytes": (
            max(live_bytes - registered, 0) if live_bytes is not None else None
        ),
        "owners": snapshot(),
    }
