"""Per-frame timeline recorder — one ordered event stream per process.

Merges the three previously separate views of a running session into one
ordered stream: the span ring (``utils/tracing.py`` — SaveWorld / LoadWorld /
AdvanceWorld / HandleRequests phases), per-peer ``network_stats`` snapshots,
and driver decisions (rollback depth, stalls, desyncs).  Each event is a
plain dict ``{"seq", "t", "kind", ...}``; events from different sessions or
lobbies carry a ``session``/``lobby`` field, so exporting one lobby's stream
is a filter over the shared order (the order itself is global — cross-lobby
interleaving is exactly what a batched-server stall investigation needs).

Recording is gated on the package enable flag (near-zero cost disabled) and
bounded by a ring (``maxlen``), mirroring the span ring's memory posture.
Export with :meth:`Timeline.export_jsonl` / :func:`export_jsonl` — the
``--telemetry-out`` flag on ``scripts/profile_tick.py`` and
``scripts/replay_tool.py`` and the desync forensics report both ride this.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Callable, Deque, List, Optional

from . import metrics as _metrics


class Timeline:
    """Bounded, ordered event recorder (see module docstring)."""

    def __init__(self, maxlen: int = 65536):
        self._events: Deque[dict] = deque(maxlen=maxlen)
        self._seq = 0
        self.dropped = 0  # events evicted past the ring bound

    def record(self, kind: str, **fields) -> None:
        """Append one event (no-op while telemetry is disabled).

        ``fields`` must be JSON-serializable; ``seq`` (process order) and
        ``t`` (perf_counter seconds) are stamped here.  Appending past the
        ring bound evicts the oldest event and counts it in :attr:`dropped`
        (mirrored to the ``timeline_events_dropped_total`` counter and
        ``telemetry.summary()``) — silent truncation would otherwise read
        as "the session only just started" in an export."""
        reg = _metrics.registry()
        if not reg.enabled:
            return
        self._seq += 1
        ev = {"seq": self._seq, "t": time.perf_counter(), "kind": kind}
        ev.update(fields)
        events = self._events
        if len(events) == events.maxlen:
            self.dropped += 1
            reg.counter(
                "timeline_events_dropped_total",
                "timeline events evicted past the ring bound",
            ).inc()
        events.append(ev)

    @property
    def maxlen(self) -> int:
        """The ring bound (events kept)."""
        return self._events.maxlen or 0

    def set_maxlen(self, maxlen: int) -> None:
        """Resize the ring, keeping the newest events that still fit
        (events shed by a shrink count as :attr:`dropped`)."""
        maxlen = int(maxlen)
        if maxlen != self._events.maxlen:
            self.dropped += max(len(self._events) - maxlen, 0)
            self._events = deque(self._events, maxlen=maxlen)

    def events(self, kind: Optional[str] = None, **field_filter) -> List[dict]:
        """Recorded events in order, optionally filtered by kind/fields."""
        out = []
        for ev in list(self._events):
            if kind is not None and ev.get("kind") != kind:
                continue
            if any(ev.get(k) != v for k, v in field_filter.items()):
                continue
            out.append(ev)
        return out

    def tail(self, n: int) -> List[dict]:
        """The last ``n`` events (the forensics-report excerpt)."""
        evs = list(self._events)
        return evs[-n:] if n > 0 else []

    def clear(self) -> None:
        """Drop all events and reset the sequence/dropped counters."""
        self._events.clear()
        self._seq = 0
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._events)

    def export_jsonl(self, path: str, **field_filter) -> int:
        """Write events (optionally filtered) as JSONL; returns the count."""
        evs = self.events(**field_filter)
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev) + "\n")
        return len(evs)


_TIMELINE = Timeline()


def timeline() -> Timeline:
    """The process-wide default timeline."""
    return _TIMELINE


def record(kind: str, **fields) -> None:
    """Record one event on the default timeline."""
    _TIMELINE.record(kind, **fields)


def export_jsonl(path: str, **field_filter) -> int:
    """Export the default timeline as JSONL (see :meth:`Timeline.export_jsonl`)."""
    return _TIMELINE.export_jsonl(path, **field_filter)


def span_sink() -> Callable[[str, float, float], None]:
    """The callback :mod:`..utils.tracing` feeds completed spans through.

    Installing it (done by ``telemetry.enable()``) merges the span ring's
    SaveWorld/LoadWorld/AdvanceWorld/... phases into the timeline as
    ``kind="span"`` events with millisecond durations."""

    def sink(name: str, t0: float, t1: float) -> None:
        _TIMELINE.record("span", name=name, t0=t0, ms=round((t1 - t0) * 1e3, 4))

    return sink
