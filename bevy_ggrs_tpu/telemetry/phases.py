"""Tick-phase latency attribution — guarded timers for the driver hot loop.

A regression in any single phase of the save→load→advance loop (input
staging, wave dispatch, checksum harvest, rollback load, store/save,
network poll, session stepping) is invisible to event counters until an
aggregate bench gate trips.  This module gives each driver a
:class:`PhaseSet`: a fixed catalog of reusable context-manager timers
(:data:`PHASES`) whose per-tick accumulations feed three sinks at tick end:

- the **flight recorder** (:mod:`.flight`, always on): one ring entry per
  tick with the phase breakdown, wall tick time and the ``unattributed_ms``
  residual — ``sum(phases) + unattributed == wall`` by construction;
- the **metrics registry** (only while telemetry is enabled): one
  ``tick_phase_ms{phase=...,owner=...}`` histogram observation per active
  phase plus ``tick_wall_ms`` / ``tick_unattributed_ms``, all on the
  log-spaced :data:`~.metrics.LATENCY_MS_BUCKETS` so
  ``telemetry.summary()["derived"]`` can report p50/p95/p99 per phase;
- **cumulative totals** on the set itself (:meth:`PhaseSet.totals`) — what
  ``bench.py``'s pipeline stage reconciles against wall time (the
  ``unattributed_ms <= 10%`` gate).

Cost discipline (the PR-1 2% budget): each timer is a preallocated object;
entering it is ONE boolean check when the set is off (flight recorder
disabled AND telemetry disabled), and two ``perf_counter()`` calls plus a
float add when on.  No registry traffic happens inside phases — histogram
observes are batched into ``end_tick``.  The hot-loop lint
(``scripts/lint_imports.py``) checks every ``phase("...")`` site in the
drivers names a catalog phase and sits inside a ``with`` block.
"""

from __future__ import annotations

import time
from typing import Optional

from . import flight as _flight
from .metrics import LATENCY_MS_BUCKETS, _label_key, registry

# The phase catalog — every hot-loop phase of the solo and batched drivers.
# scripts/lint_imports.py mirrors this set (stdlib-only, cannot import the
# package); tests/test_phases.py asserts the two stay identical.
PHASES = (
    "net_poll",          # poll_remote_clients + event drain + net stats
    "session_step",      # session advance_frame (input/ack/checksum protocol)
    "stage_inputs",      # fill the persistent host staging buffers
    "wave_dispatch",     # fused device program submission (+ readback start)
    "readback_harvest",  # collect landed async checksum copies / sync drain
    "rollback_load",     # ring rollback + world restore
    "store_save",        # ring pushes + save-cell publication
)


def _quantile(sorted_vals, q: float) -> float:
    """Linear-interpolated quantile of an already-sorted list."""
    idx = q * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def phase_breakdown(entries, qs=(0.5, 0.95, 0.99)) -> dict:
    """EXACT per-phase latency percentiles over flight-recorder ``tick``
    entries (the ``--phase-breakdown`` table of scripts/profile_tick.py and
    scripts/replay_tool.py).

    Unlike the registry histograms — which estimate percentiles from
    log-spaced buckets — the flight ring holds each tick's exact
    millisecond values, so a bounded window gets exact quantiles, and it
    works without telemetry ever having been enabled.  Returns
    ``{phase: {"p50": ..., "p95": ..., "p99": ..., "count": n}}`` in
    catalog order plus ``(wall)`` / ``(unattributed)`` rows."""
    series: dict = {}
    for e in entries:
        if e.get("kind") != "tick":
            continue
        for name, ms in e.get("phases", {}).items():
            series.setdefault(name, []).append(ms)
        series.setdefault("(wall)", []).append(e.get("wall_ms", 0.0))
        series.setdefault("(unattributed)", []).append(
            e.get("unattributed_ms", 0.0)
        )
    out = {}
    order = [*PHASES, "(unattributed)", "(wall)"]
    for name in order:
        vals = series.get(name)
        if not vals:
            continue
        vals.sort()
        row = {f"p{q * 100:g}": round(_quantile(vals, q), 4) for q in qs}
        row["count"] = len(vals)
        out[name] = row
    return out


def format_phase_table(breakdown: dict) -> str:
    """Render a :func:`phase_breakdown` dict as the aligned text table the
    profiling scripts print."""
    if not breakdown:
        return "  (no flight-recorder tick entries in the window)"
    qcols = [k for k in next(iter(breakdown.values())) if k != "count"]
    lines = [
        "  " + f"{'phase':18s} {'count':>6} "
        + " ".join(f"{q + ' ms':>10}" for q in qcols)
    ]
    for name, row in breakdown.items():
        lines.append(
            f"  {name:18s} {row['count']:>6} "
            + " ".join(f"{row[q]:>10.3f}" for q in qcols)
        )
    return "\n".join(lines)


class _Phase:
    """One reusable guarded timer: ``with ps.phase("wave_dispatch"): ...``.

    Not reentrant (each catalog phase times a single non-nested region of
    the tick).  When the owning set is off, ``__enter__`` is one boolean
    check and ``__exit__`` one ``is None`` check."""

    __slots__ = ("_ps", "_i", "_t0")

    def __init__(self, ps: "PhaseSet", i: int):
        self._ps = ps
        self._i = i
        self._t0: Optional[float] = None

    def __enter__(self) -> "_Phase":
        if self._ps._on:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t0 = self._t0
        if t0 is not None:
            self._ps._acc[self._i] += time.perf_counter() - t0
            self._t0 = None
        return False


class PhaseSet:
    """Per-driver phase accounting: timers, per-tick flush, run totals.

    One instance per runner (``owner`` labels its series: ``"solo"`` /
    ``"batched"``).  The driver calls :meth:`begin_tick` at the top of its
    update, runs phases via ``with self._phases.phase("..."):``, notes
    decisions (:meth:`note_rollback` / :meth:`note_advances`), and calls
    :meth:`end_tick` once per tick that did work."""

    def __init__(self, owner: str = "solo"):
        self.owner = owner
        self._reg = registry()
        self._acc = [0.0] * len(PHASES)
        self._timers = {name: _Phase(self, i) for i, name in enumerate(PHASES)}
        self._on = False
        self._t_tick = 0.0
        self._tick_rollbacks = 0
        self._tick_rollback_depth = 0
        self._tick_advances = 0
        # cumulative run totals (always-on; the bench reconciliation source)
        self.ticks = 0
        self.wall_seconds = 0.0
        self.attributed_seconds = 0.0
        self.unattributed_seconds = 0.0
        self.phase_seconds = {name: 0.0 for name in PHASES}
        # registry handles, re-resolved when the registry generation moves
        self._gen = -1
        self._hist = None
        self._h_wall = None
        self._h_unattr = None
        self._keys = {}
        self._owner_key = ()

    @property
    def on(self) -> bool:
        """Whether this tick is being recorded (set by :meth:`begin_tick`:
        flight recorder OR telemetry enabled).  Drivers gate optional
        ``end_tick(**extra)`` computations on it so the fully-disabled tick
        path stays one boolean check."""
        return self._on

    def phase(self, name: str) -> _Phase:
        """The catalog timer for ``name`` (KeyError on a non-catalog name —
        a typo here would silently grow ``unattributed_ms``)."""
        return self._timers[name]

    def begin_tick(self) -> None:
        """Arm the timers for one driver tick (refreshes the on/off gate:
        flight recorder OR telemetry enabled)."""
        self._on = _flight._FLIGHT.enabled or self._reg.enabled
        if self._on:
            self._t_tick = time.perf_counter()
            self._tick_rollbacks = 0
            self._tick_rollback_depth = 0
            self._tick_advances = 0

    def note_rollback(self, depth: int) -> None:
        """Count one rollback decision into this tick's flight entry."""
        if self._on:
            self._tick_rollbacks += 1
            if depth > self._tick_rollback_depth:
                self._tick_rollback_depth = depth

    def note_advances(self, n: int) -> None:
        """Count ``n`` advanced frames into this tick's flight entry."""
        if self._on:
            self._tick_advances += n

    def _rebind(self) -> None:
        reg = self._reg
        self._hist = reg.histogram(
            "tick_phase_ms",
            "per-tick milliseconds spent in each hot-loop phase",
            buckets=LATENCY_MS_BUCKETS,
        )
        self._h_wall = reg.histogram(
            "tick_wall_ms", "wall milliseconds per driver tick",
            buckets=LATENCY_MS_BUCKETS,
        )
        self._h_unattr = reg.histogram(
            "tick_unattributed_ms",
            "per-tick wall milliseconds not covered by any phase timer",
            buckets=LATENCY_MS_BUCKETS,
        )
        self._keys = {
            name: _label_key({"phase": name, "owner": self.owner})
            for name in PHASES
        }
        self._owner_key = _label_key({"owner": self.owner})
        self._gen = reg.generation

    def end_tick(self, frame: Optional[int] = None, **extra) -> None:
        """Flush one tick's accumulations: flight entry, histograms,
        cumulative totals.  ``extra`` fields ride into the flight entry
        (e.g. ``lobbies=M`` for the batched driver)."""
        if not self._on:
            return
        wall = time.perf_counter() - self._t_tick
        attributed = 0.0
        phases_ms = {}
        acc = self._acc
        tot = self.phase_seconds
        for i, name in enumerate(PHASES):
            v = acc[i]
            if v:
                attributed += v
                tot[name] += v
                phases_ms[name] = round(v * 1e3, 4)
                acc[i] = 0.0
        unattr = max(wall - attributed, 0.0)
        self.ticks += 1
        self.wall_seconds += wall
        self.attributed_seconds += attributed
        self.unattributed_seconds += unattr
        fr = _flight._FLIGHT
        if fr.enabled:
            fr.record(
                "tick", owner=self.owner, frame=frame,
                wall_ms=round(wall * 1e3, 4), phases=phases_ms,
                unattributed_ms=round(unattr * 1e3, 4),
                rollbacks=self._tick_rollbacks,
                rollback_depth=self._tick_rollback_depth,
                advances=self._tick_advances, **extra,
            )
        reg = self._reg
        if reg.enabled:
            if self._gen != reg.generation:
                self._rebind()
            keys = self._keys
            hist = self._hist
            for name, ms in phases_ms.items():
                hist.observe_key(keys[name], ms)
            self._h_wall.observe_key(self._owner_key, wall * 1e3)
            self._h_unattr.observe_key(self._owner_key, unattr * 1e3)

    def totals(self) -> dict:
        """Cumulative attribution since construction: per-phase seconds,
        wall/attributed/unattributed seconds, tick count, and the
        ``unattributed_pct`` the pipeline bench stage gates on."""
        return {
            "owner": self.owner,
            "ticks": self.ticks,
            "wall_seconds": round(self.wall_seconds, 6),
            "attributed_seconds": round(self.attributed_seconds, 6),
            "unattributed_seconds": round(self.unattributed_seconds, 6),
            "unattributed_pct": round(
                100.0 * self.unattributed_seconds / self.wall_seconds, 2
            ) if self.wall_seconds else 0.0,
            "phase_seconds": {
                k: round(v, 6) for k, v in self.phase_seconds.items() if v
            },
        }
