"""The fleet's unit of work: a deterministic, checkpointable lobby sim.

A :class:`LobbySim` is a server-side lockstep lobby — app + world + frame +
an input queue — that a fleet worker hosts, advances in chunks, and can
freeze into a single checkpoint artifact (world + frame + the unsimulated
input-queue tail, via :mod:`..snapshot.persist`) for live migration or
failover.  Determinism contract: given the same :class:`LobbySpec` and the
same submitted inputs, a lobby produces bit-identical checksums at every
frame on every host, whatever the chunking of its advances — the catalog
apps are built with ``canonical_depth`` so every advance runs through ONE
compiled program regardless of how a migration split the frame sequence
(docs/determinism.md "One program to advance them all").

Input modes:

- ``synthetic`` — inputs are a pure function of ``(spec.seed, frame)``
  (counter-based seeding, no sequential RNG state to checkpoint); the
  fleet bench drives thousands of frames this way and any host can
  regenerate any frame's inputs after a failover.
- ``external`` — inputs arrive via :meth:`LobbySim.submit_input`; the sim
  only advances through frames whose inputs are queued, and the
  *unsimulated tail rides the checkpoint* — a migrated lobby must consume
  exactly the inputs its source had queued, or it desyncs.
"""

from __future__ import annotations

import dataclasses
import io
from typing import Callable, Dict, Optional

import numpy as np

from ..snapshot.checksum import checksum_to_int
from ..snapshot.persist import load_checkpoint, save_world, schema_digest

# default per-advance chunk == the canonical program depth of catalog apps:
# one dispatch per chunk, and the padded program keeps partial chunks
# (barrier stops, target stops) bit-identical to full ones
LOBBY_CHUNK = 16


@dataclasses.dataclass(frozen=True)
class LobbySpec:
    """Everything needed to (re)build a lobby anywhere in the fleet.

    Travels as JSON in PLACE/RESUME/SUBMIT datagrams; ``est_bytes`` is the
    admission-control sizing hint (device-resident bytes the lobby will
    pin), defaulted from the app's world size when 0."""

    lobby_id: str
    app: str = "stress_soa"
    entities: int = 256
    players: int = 2
    seed: int = 0
    target_frames: int = 600
    input_mode: str = "synthetic"  # or "external"
    est_bytes: int = 0

    def to_json(self) -> dict:
        """The wire form (plain dict for protocol JSON tails)."""
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: dict) -> "LobbySpec":
        """Rebuild from the wire form; unknown keys are ignored (forward
        compatibility across fleet versions)."""
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in obj.items() if k in fields})


def _make_stress_soa(spec: LobbySpec):
    from ..models import stress_soa

    return stress_soa.make_app(
        spec.entities, seed=spec.seed, canonical_depth=LOBBY_CHUNK
    )


def _make_box_game(spec: LobbySpec):
    from ..models import box_game

    return box_game.make_app(
        num_players=spec.players, canonical_depth=LOBBY_CHUNK
    )


# app catalog: name -> App factory.  Every entry MUST pass canonical_depth
# (see module docstring) — a per-length-program app would drift across
# migration chunk boundaries.
APP_CATALOG: Dict[str, Callable[[LobbySpec], object]] = {
    "stress_soa": _make_stress_soa,
    "box_game": _make_box_game,
}


def synthetic_inputs(spec: LobbySpec, app, frame: int) -> np.ndarray:
    """The synthetic per-frame input row ``[players, *input_shape]``.

    Counter-based seeding — a pure function of (seed, frame) — so a resumed
    or failed-over lobby regenerates the identical stream with no RNG state
    in the checkpoint."""
    rng = np.random.default_rng((spec.seed, frame))
    shape = (app.num_players, *app.input_shape)
    if np.issubdtype(app.input_dtype, np.integer):
        return rng.integers(0, 16, size=shape).astype(app.input_dtype)
    return rng.uniform(-1, 1, size=shape).astype(app.input_dtype)


class LobbySim:
    """One hosted lobby: app + world + frame + input queue, checkpointable.

    Drive with :meth:`step`; freeze with :meth:`checkpoint_bytes`; thaw on
    another host with :meth:`restore`.  ``frame`` is the last simulated
    (and, lockstep, confirmed) frame; the queue holds inputs for frames
    > ``frame``."""

    def __init__(self, spec: LobbySpec, _restored=None):
        if spec.app not in APP_CATALOG:
            raise ValueError(
                f"unknown lobby app {spec.app!r}; catalog: "
                f"{sorted(APP_CATALOG)}"
            )
        if spec.input_mode not in ("synthetic", "external"):
            raise ValueError("input_mode must be 'synthetic' or 'external'")
        self.spec = spec
        self.app = APP_CATALOG[spec.app](spec)
        # pending inputs: frame -> [players, *input_shape] (external mode;
        # synthetic mode generates on demand)
        self.pending: Dict[int, np.ndarray] = {}
        if _restored is not None:
            self.world, self.frame = _restored
        else:
            self.world = self.app.init_state()
            self.frame = 0
        self._status_row = np.zeros((self.app.num_players,), np.int8)
        self._last_checksum: Optional[int] = None

    # -- inputs ------------------------------------------------------------

    def submit_input(self, frame: int, row) -> None:
        """Queue the input row for ``frame`` (external mode).  Frames at or
        below the simulated frame are already history — rejecting them here
        is what makes the checkpoint tail authoritative."""
        if self.spec.input_mode != "external":
            raise ValueError("submit_input on a synthetic-input lobby")
        if frame <= self.frame:
            raise ValueError(
                f"input for frame {frame} but lobby already simulated "
                f"frame {self.frame}"
            )
        row = np.asarray(row, self.app.input_dtype)
        want = (self.app.num_players, *self.app.input_shape)
        if row.shape != want:
            raise ValueError(f"input row shape {row.shape} != {want}")
        self.pending[frame] = row

    def _input_row(self, frame: int) -> Optional[np.ndarray]:
        if self.spec.input_mode == "synthetic":
            got = self.pending.pop(frame, None)
            if got is not None:
                return got
            return synthetic_inputs(self.spec, self.app, frame)
        return self.pending.pop(frame, None)

    def ready_frames(self, limit: int) -> int:
        """How many frames past ``self.frame`` could advance right now
        (bounded by ``limit``, the target frame, and — external mode — the
        contiguous queued prefix)."""
        room = min(limit, self.spec.target_frames - self.frame)
        if room <= 0:
            return 0
        if self.spec.input_mode == "synthetic":
            return room
        n = 0
        while n < room and (self.frame + n + 1) in self.pending:
            n += 1
        return n

    # -- advancing ---------------------------------------------------------

    @property
    def done(self) -> bool:
        """True once the lobby simulated its target frame."""
        return self.frame >= self.spec.target_frames

    def step(self, max_frames: int = LOBBY_CHUNK) -> int:
        """Advance up to ``max_frames`` frames in one chunked dispatch;
        returns how many frames actually advanced.  The last chunk's final
        checksum is retained for :meth:`checksum`."""
        k = self.ready_frames(max_frames)
        if k <= 0:
            return 0
        rows = []
        for i in range(1, k + 1):
            row = self._input_row(self.frame + i)
            assert row is not None  # ready_frames counted it
            rows.append(row)
        inputs_seq = np.stack(rows)
        status_seq = np.broadcast_to(
            self._status_row, (k, self.app.num_players)
        )
        final, _stacked, checks = self.app.resim_fn(
            self.world, inputs_seq, np.ascontiguousarray(status_seq),
            self.frame,
        )
        self.world = final
        self.frame += k
        self._last_checksum = checksum_to_int(checks[k - 1])
        return k

    def run_to(self, frame: int, chunk: int = LOBBY_CHUNK) -> None:
        """Advance to exactly ``frame`` (synthetic mode / tests)."""
        while self.frame < min(frame, self.spec.target_frames):
            if self.step(min(chunk, frame - self.frame)) == 0:
                break

    def checksum(self) -> int:
        """The 64-bit world checksum at the current frame (forces a device
        readback — control-plane use, not hot-loop)."""
        cs = self.app.checksum_fn(self.world)
        return checksum_to_int(cs)

    # -- checkpoint / restore ---------------------------------------------

    def checkpoint_bytes(self) -> bytes:
        """Freeze world + frame + the unsimulated input-queue tail into one
        npz blob (the migration/failover artifact)."""
        tail = sorted(f for f in self.pending if f > self.frame)
        extras = {}
        if tail:
            extras["tail_frames"] = np.asarray(tail, np.int64)
            extras["tail_inputs"] = np.stack(
                [self.pending[f] for f in tail]
            )
        buf = io.BytesIO()
        save_world(buf, self.app.reg, self.world, frame=self.frame,
                   extras=extras)
        return buf.getvalue()

    @classmethod
    def restore(cls, spec: LobbySpec, blob: bytes) -> "LobbySim":
        """Thaw a checkpoint into a fresh sim (schema-checked, strict
        dtypes — see snapshot/persist.py) and re-queue its input tail."""
        tmp = cls(spec)  # builds the app/registry the checkpoint must match
        ck = load_checkpoint(io.BytesIO(blob), tmp.app.reg)
        sim = cls(spec, _restored=(ck.world, ck.frame))
        frames = ck.extras.get("tail_frames")
        if frames is not None:
            inputs = ck.extras["tail_inputs"]
            for i, f in enumerate(frames.tolist()):
                sim.pending[int(f)] = np.asarray(
                    inputs[i], sim.app.input_dtype
                )
        return sim

    def state_digest(self) -> str:
        """Registry schema digest (control-plane sanity: a RESUME against a
        worker running a different build fails fast, by name)."""
        return schema_digest(self.app.reg)

    def est_bytes(self) -> int:
        """Device-resident footprint estimate for admission control: the
        world pytree's nbytes (canonical programs keep one resident world
        per lobby on the worker)."""
        import jax

        return int(sum(
            np.asarray(x).nbytes for x in jax.tree.leaves(self.world)
        ))


def spec_est_bytes(spec: LobbySpec) -> int:
    """Admission sizing WITHOUT building device state: world bytes computed
    from the registry's template shapes (host-side numpy only)."""
    if spec.est_bytes:
        return int(spec.est_bytes)
    app = APP_CATALOG[spec.app](spec)
    import jax

    template = app.reg.init_state()
    return int(sum(
        np.asarray(x).nbytes for x in jax.tree.leaves(template)
    ))


def spec_to_wire(spec: LobbySpec) -> dict:
    """Spec -> wire dict (alias of :meth:`LobbySpec.to_json`, kept as a
    module function for symmetry with :func:`spec_from_wire`)."""
    return spec.to_json()


def spec_from_wire(obj: dict) -> LobbySpec:
    """Wire dict -> spec (lenient; see :meth:`LobbySpec.from_json`)."""
    return LobbySpec.from_json(obj)


def checksum_hex(value: int) -> str:
    """64-bit checksum -> fixed-width hex for DONE datagrams."""
    return f"{value & 0xFFFFFFFFFFFFFFFF:016x}"
