"""Fleet worker: one host process that registers, heartbeats, and runs lobbies.

A :class:`FleetWorker` owns a UDP socket and a dict of hosted
:class:`~.lobby.LobbySim` instances.  Drive it with :meth:`poll` from a loop
(or :meth:`run` in the ``scripts/fleet_worker.py`` CLI); each poll drains
the socket (PLACE / DRAIN / RESUME / DROP from the scheduler), advances
every runnable lobby by a bounded frame budget, then does the periodic
housekeeping: heartbeats, checkpoint shipping, DONE reports.

Reliability posture (everything is UDP): the worker, not the scheduler, is
the retry engine for its own uplink — REGISTER repeats until any scheduler
datagram arrives, heartbeats repeat forever, and every checkpoint re-ships
on a timer until the scheduler's CKPT_ACK for that exact (lobby, frame)
lands.  Scheduler-to-worker commands are likewise idempotent on this side:
a re-PLACE of a hosted lobby just re-sends PLACE_OK, a re-DRAIN re-ships
the barrier checkpoint.

Checkpoint shipping doubles as the failover plan: every
``ckpt_every_frames`` simulated frames the worker cuts a confirmed
checkpoint (world + frame + input tail, snapshot/persist.py) and ships it
to the scheduler, so when this process dies without warning the scheduler
holds a last-confirmed-frame artifact to resume from (see
fleet/scheduler.py failover)."""

from __future__ import annotations

import dataclasses
import logging
import socket as _socket
import time
from typing import Dict, Optional, Tuple

from .. import telemetry
from . import protocol as P
from .lobby import LOBBY_CHUNK, LobbySim, LobbySpec, checksum_hex

log = logging.getLogger("bevy_ggrs_tpu.fleet.worker")

HEARTBEAT_S = 0.25  # control-plane cadence (low-rate by design)
CKPT_RESHIP_S = 0.5  # unacked checkpoint retry interval
CKPT_EVERY_FRAMES = 120  # periodic confirmed-checkpoint cadence
# digest-suppressed heartbeats: force a full stats payload every N beats so
# a lost full (or a restarted scheduler that never saw one) self-heals
# within N * heartbeat_s instead of stranding liveness on a stale digest
FULL_HEARTBEAT_EVERY = 8


@dataclasses.dataclass
class _Shipment:
    """One in-flight checkpoint upload: re-sent until CKPT_ACKed."""

    frame: int
    datagrams: list
    last_sent: float = 0.0
    acked: bool = False


class _Hosted:
    """Book-keeping wrapper around one hosted LobbySim."""

    def __init__(self, sim: LobbySim):
        self.sim = sim
        self.state = "running"  # running | draining | drained | done
        self.barrier: Optional[int] = None
        self.shipment: Optional[_Shipment] = None
        self.last_ckpt_frame = 0
        self.done_sent = False
        self.final_checksum: Optional[int] = None
        # realtime pacing anchor: (wall time, sim frame) at hosting start —
        # restored lobbies anchor at their restore frame, not 0
        self.pace_anchor = (time.monotonic(), sim.frame)


class FleetWorker:
    """One fleet host: registers with the scheduler, runs placed lobbies,
    drains/ships/restores them on command.

    ``step_budget`` bounds how many frames each lobby advances per poll so
    one long lobby cannot starve the control plane of polls.

    ``pace_fps`` > 0 caps each RUNNING lobby to realtime cadence (a game
    ticks at a fixed rate; an unpaced CPU sim clears a whole match between
    two heartbeats, which makes scheduler frame knowledge useless).
    Draining is deliberately unpaced: once a migration barrier is set the
    only goal is to reach it, and every paced frame there is pure added
    downtime."""

    def __init__(
        self,
        worker_id: str,
        scheduler_addr: Tuple[str, int],
        capacity: int = 4,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_s: float = HEARTBEAT_S,
        ckpt_every_frames: int = CKPT_EVERY_FRAMES,
        step_budget: int = LOBBY_CHUNK,
        pace_fps: float = 0.0,
    ):
        self.worker_id = worker_id
        self.scheduler_addr = scheduler_addr
        self.capacity = int(capacity)
        self.heartbeat_s = heartbeat_s
        self.ckpt_every_frames = int(ckpt_every_frames)
        self.step_budget = int(step_budget)
        self.pace_fps = float(pace_fps)
        self.lobbies: Dict[str, _Hosted] = {}
        # RESUME orders awaiting their checkpoint chunks:
        # lobby_id -> (frame, LobbySpec)
        self._resuming: Dict[str, Tuple[int, LobbySpec]] = {}
        self._assembler = P.ChunkAssembler()
        self._last_heartbeat = 0.0
        self._registered_ack = False
        # heartbeat suppression state: last full stats payload + its digest
        self._last_stats: Optional[dict] = None
        self._last_digest = ""
        self._hb_seq = 0
        self._beats_since_full = 0
        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind((host, port))

    @property
    def local_addr(self) -> Tuple[str, int]:
        """The bound (host, port) of the worker socket."""
        return self._sock.getsockname()

    def close(self) -> None:
        """Release the socket (tests; the CLI just exits)."""
        self._sock.close()

    # -- outbound ----------------------------------------------------------

    def _send(self, data: bytes) -> None:
        try:
            self._sock.sendto(data, self.scheduler_addr)
        except OSError:
            pass  # scheduler gone; heartbeat/reship timers keep retrying

    def _wire(self, op: str, lid: str = "", frame: int = 0) -> None:
        """Stamp one control-plane wire event onto the timeline — merged
        fleet traces pair these with the scheduler's side into flow arrows
        (telemetry/trace.py) and use the pairs for clock alignment."""
        telemetry.record("fleet_wire", track=f"worker:{self.worker_id}",
                         op=op, lid=lid, frame=frame)

    def register(self) -> None:
        """(Re-)announce this worker; repeated until the scheduler talks
        back (any inbound datagram counts as the ack)."""
        self._send(P.encode_register(self.worker_id, self.capacity))

    def _stats(self) -> dict:
        """The heartbeat JSON: capacity, per-lobby status, load signals."""
        lob = {}
        remaining = []
        for lid, h in self.lobbies.items():
            lob[lid] = {"frame": h.sim.frame, "state": h.state}
            remaining.append(
                max(0, h.sim.spec.target_frames - h.sim.frame)
            )
        # load-skew signal for placement: busiest lobby's remaining frames
        # over the mean (1.0 = balanced / idle), same max-over-mean shape as
        # the ShardPlanner's shard_imbalance_ratio gauge
        mean = sum(remaining) / len(remaining) if remaining else 0.0
        imbalance = (max(remaining) / mean) if mean > 0 else 1.0
        qos = telemetry.qos_snapshot()["lobby_qos_score"]
        return {
            "capacity": self.capacity,
            "lobbies": lob,
            "shard_imbalance_ratio": round(imbalance, 4),
            "device_resident_bytes": telemetry.devmem.total(),
            "lobby_qos_score": {
                lid: qos.get(lid, qos.get("default", 100.0))
                for lid in self.lobbies
            },
        }

    def _heartbeat(self, now: float) -> None:
        if now - self._last_heartbeat < self.heartbeat_s:
            return
        self._last_heartbeat = now
        if not self._registered_ack:
            self.register()
        stats = self._stats()
        self._hb_seq += 1
        if (stats == self._last_stats
                and self._beats_since_full < FULL_HEARTBEAT_EVERY):
            # unchanged payload: skip the JSON re-serialize and ship a
            # liveness-only HB_SEQ carrying the last full payload's digest
            self._beats_since_full += 1
            self._send(P.encode_heartbeat_seq(
                self.worker_id, self._hb_seq, self._last_digest
            ))
            telemetry.count(
                "fleet_heartbeat_suppressed_total",
                help="liveness-only heartbeats sent in place of an "
                     "unchanged stats payload",
            )
        else:
            self._last_stats = stats
            self._last_digest = P.stats_digest(stats)
            self._beats_since_full = 0
            self._send(P.encode_heartbeat(self.worker_id, stats))
        # re-announce finished lobbies at heartbeat cadence: DONE has no
        # ack type, so a lost datagram must not strand the scheduler in
        # "running" forever (the lobby stays hosted until DROP anyway)
        for lid, h in self.lobbies.items():
            if h.state == "done" and h.done_sent:
                self._send(P.encode_done(
                    lid, h.sim.frame, checksum_hex(h.final_checksum)
                ))

    # -- inbound -----------------------------------------------------------

    def _handle(self, msg: P.Msg) -> None:
        # any scheduler datagram proves the REGISTER got through
        self._registered_ack = True
        if msg.kind == P.T_PLACE:
            self._on_place(msg)
        elif msg.kind == P.T_DRAIN:
            self._on_drain(msg)
        elif msg.kind == P.T_RESUME:
            self._on_resume(msg)
        elif msg.kind == P.T_CKPT:
            self._on_ckpt_chunk(msg)
        elif msg.kind == P.T_CKPT_ACK:
            h = self.lobbies.get(msg.a)
            if h and h.shipment and h.shipment.frame == msg.frame:
                h.shipment.acked = True
        elif msg.kind == P.T_DROP:
            if msg.a in self.lobbies:
                log.info("worker %s: dropping lobby %s", self.worker_id, msg.a)
                del self.lobbies[msg.a]
                self._wire("DROP_RECV", msg.a)
            self._resuming.pop(msg.a, None)

    def _on_place(self, msg: P.Msg) -> None:
        if msg.a in self.lobbies:  # idempotent re-PLACE
            self._send(P.encode_place_ok(msg.a, self.lobbies[msg.a].sim.frame))
            return
        spec = LobbySpec.from_json(msg.obj)
        sim = LobbySim(spec)
        self.lobbies[msg.a] = _Hosted(sim)
        log.info("worker %s: placed lobby %s (%s, %d entities)",
                 self.worker_id, msg.a, spec.app, spec.entities)
        self._send(P.encode_place_ok(msg.a, sim.frame))
        self._wire("PLACE_OK", msg.a, sim.frame)

    def _on_drain(self, msg: P.Msg) -> None:
        h = self.lobbies.get(msg.a)
        if h is None:
            return
        if h.state == "drained" and h.barrier == msg.frame:
            self._reship(h, time.monotonic(), force=True)  # lost CKPT? again
            return
        # a barrier at or behind the current frame drains immediately AT the
        # current frame (the scheduler's view can lag a heartbeat)
        h.state = "draining"
        h.barrier = max(msg.frame, h.sim.frame)

    def _on_resume(self, msg: P.Msg) -> None:
        if msg.a in self.lobbies:  # idempotent re-RESUME after completion
            self._send(P.encode_resume_ok(msg.a, self.lobbies[msg.a].sim.frame))
            return
        self._resuming[msg.a] = (msg.frame, LobbySpec.from_json(msg.obj))

    def _on_ckpt_chunk(self, msg: P.Msg) -> None:
        order = self._resuming.get(msg.a)
        if order is None or order[0] != msg.frame:
            return
        blob = self._assembler.offer(msg)
        if blob is None:
            return
        frame, spec = self._resuming.pop(msg.a)
        sim = LobbySim.restore(spec, blob)
        h = _Hosted(sim)
        h.last_ckpt_frame = sim.frame
        self.lobbies[msg.a] = h
        log.info("worker %s: resumed lobby %s at frame %d",
                 self.worker_id, msg.a, sim.frame)
        self._send(P.encode_resume_ok(msg.a, sim.frame))
        self._wire("RESUME_OK", msg.a, sim.frame)
        # a restore (app build + first-step compile) can stall this worker
        # past the scheduler's heartbeat timeout; heartbeat immediately so
        # the stall window is as small as the work, not work + cadence
        self._last_heartbeat = 0.0

    # -- checkpoint shipping ----------------------------------------------

    def _cut_shipment(self, lid: str, h: _Hosted) -> None:
        blob = h.sim.checkpoint_bytes()
        h.shipment = _Shipment(
            frame=h.sim.frame,
            datagrams=P.chunk_checkpoint(lid, h.sim.frame, blob),
        )
        h.last_ckpt_frame = h.sim.frame

    def _reship(self, h: _Hosted, now: float, force: bool = False) -> None:
        s = h.shipment
        if s is None or (s.acked and not force):
            return
        if not force and now - s.last_sent < CKPT_RESHIP_S:
            return
        s.last_sent = now
        s.acked = s.acked and not force
        for d in s.datagrams:
            self._send(d)

    # -- main loop ---------------------------------------------------------

    def _advance(self, lid: str, h: _Hosted) -> None:
        if h.state == "running":
            budget = self.step_budget
            if self.pace_fps > 0:
                t0, f0 = h.pace_anchor
                now = time.monotonic()
                allowed = f0 + int((now - t0) * self.pace_fps)
                if allowed - h.sim.frame > self.step_budget:
                    # fell behind realtime (first-step compile, restore):
                    # don't fast-forward the backlog — re-anchor at the
                    # present, exactly like a game dropping missed ticks
                    h.pace_anchor = (now, h.sim.frame)
                    allowed = h.sim.frame + self.step_budget
                budget = min(budget, allowed - h.sim.frame)
        elif h.state == "draining":
            budget = min(self.step_budget, h.barrier - h.sim.frame)
        else:
            return
        if budget > 0:
            h.sim.step(budget)
        if h.state == "draining" and h.sim.frame >= h.barrier:
            # at the barrier: cut + ship the migration checkpoint
            self._cut_shipment(lid, h)
            self._reship(h, time.monotonic(), force=True)
            h.state = "drained"
            self._wire("DRAINED", lid, h.barrier)
            log.info("worker %s: drained lobby %s at barrier %d",
                     self.worker_id, lid, h.barrier)
            return
        if h.state == "running":
            if h.sim.done:
                h.state = "done"
                h.final_checksum = h.sim.checksum()
            elif (h.sim.frame - h.last_ckpt_frame >= self.ckpt_every_frames
                  and (h.shipment is None or h.shipment.acked)):
                # periodic confirmed checkpoint: the scheduler's failover
                # source.  Never more than one unacked upload per lobby
                self._cut_shipment(lid, h)

    def poll(self) -> None:
        """One scheduling quantum: drain the socket, advance lobbies by the
        step budget, ship/re-ship checkpoints, heartbeat, report DONEs."""
        while True:
            try:
                data, addr = self._sock.recvfrom(65536)
            except (BlockingIOError, OSError):
                break
            msg = P.decode(data)
            if msg is not None:
                self._handle(msg)
            else:
                P.note_malformed(addr)
        for lid, h in list(self.lobbies.items()):
            self._advance(lid, h)
            now = time.monotonic()
            self._reship(h, now)
            # heartbeat BETWEEN lobby advances too: a poll over several
            # freshly-placed lobbies runs their first-step compiles
            # back-to-back, and the un-interleaved stall was long enough
            # to get a healthy worker declared dead
            self._heartbeat(now)
            if h.state == "done" and not h.done_sent:
                self._send(P.encode_done(
                    lid, h.sim.frame, checksum_hex(h.final_checksum)
                ))
                h.done_sent = True
                log.info("worker %s: lobby %s done at frame %d (%s)",
                         self.worker_id, lid, h.sim.frame,
                         checksum_hex(h.final_checksum))
        self._heartbeat(time.monotonic())

    def run(self, duration_s: Optional[float] = None,
            idle_sleep_s: float = 0.005) -> None:
        """Poll until ``duration_s`` elapses (forever when None) — the
        ``scripts/fleet_worker.py`` main loop."""
        self.register()
        t0 = time.monotonic()
        while duration_s is None or time.monotonic() - t0 < duration_s:
            self.poll()
            time.sleep(idle_sleep_s)
