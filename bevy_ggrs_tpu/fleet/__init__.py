"""Fleet layer: multi-host matchmaking, placement, migration, failover.

The packages below promote the single-process room server
(session/room.py) into a fleet control plane:

- :mod:`.protocol` — the wire messages (riding the room framing) between
  scheduler, workers, and clients, plus chunked checkpoint transfer.
- :mod:`.lobby` — the unit of work: a deterministic, checkpointable
  :class:`~.lobby.LobbySim` built from canonical-depth apps so migration
  cannot change bits.
- :mod:`.worker` — one host process: registers, heartbeats, runs placed
  lobbies, drains/ships/restores checkpoints.
- :mod:`.scheduler` — the matchmaker: QoS/bytes-aware greedy placement,
  wire-visible admission control, drain-at-barrier live migration, and
  heartbeat-timeout failover from last-confirmed checkpoints.
- :mod:`.observe` — scheduler-side federation: heartbeat-derived metric
  time-series, SLO burn-rate alerting, and the fleet HTTP surface
  (``/fleet``, ``/qos``, federated ``/metrics``).

See docs/architecture.md "Fleet scheduling & migration" for the lifecycle
diagrams and docs/observability.md for the ``fleet_*`` metric families."""

from .lobby import (
    APP_CATALOG,
    LOBBY_CHUNK,
    LobbySim,
    LobbySpec,
    checksum_hex,
    spec_est_bytes,
    synthetic_inputs,
)
from .observe import (
    AlertEvent,
    FleetObserver,
    SLO,
    SeriesRing,
    default_slos,
    fleet_routes,
    start_fleet_exporter,
)
from .protocol import ChunkAssembler, Msg, chunk_checkpoint, decode
from .scheduler import FleetClient, FleetScheduler, LobbyRecord, WorkerInfo
from .worker import FleetWorker

__all__ = [
    "AlertEvent",
    "FleetObserver",
    "SLO",
    "SeriesRing",
    "default_slos",
    "fleet_routes",
    "start_fleet_exporter",
    "APP_CATALOG",
    "LOBBY_CHUNK",
    "LobbySim",
    "LobbySpec",
    "checksum_hex",
    "spec_est_bytes",
    "synthetic_inputs",
    "ChunkAssembler",
    "Msg",
    "chunk_checkpoint",
    "decode",
    "FleetClient",
    "FleetScheduler",
    "LobbyRecord",
    "WorkerInfo",
    "FleetWorker",
]
