"""Fleet scheduler: matchmaker + placement + admission + migration + failover.

The :class:`FleetScheduler` is the control-plane brain promoted out of
``scripts/room_server.py``: one UDP endpoint that workers register and
heartbeat against, clients submit lobbies to, and that owns every placement
decision.  Like the room server it is entirely ``poll()``-driven and
non-blocking — run it from a loop, a thread, or ``scripts/fleet_scheduler.py``.

Placement is greedy bin-packing over live heartbeat state: a lobby goes to
the *feasible* worker (slot free, bytes budget not exceeded) with the best
score — emptiest by slots first, then lowest estimated device-resident
bytes, then best reported QoS floor.  Infeasible everywhere = admission
reject, ON THE WIRE, with the reason (``capacity`` / ``memory`` /
``no_workers``) — a client is never left to infer rejection from silence,
and every reject increments ``admission_rejects_total{reason}``.

Live migration (:meth:`migrate`) is a drain-and-resume handshake pinned to
a confirmed-frame barrier: DRAIN(src, barrier) → the source advances
exactly TO the barrier, checkpoints (world + frame + input tail), ships it
here → RESUME(dst) + chunks → dst restores and RESUME_OK → DROP(src).
Downtime is measured scheduler-side — final-checkpoint-complete to
RESUME_OK arrival, both on this process's clock (cross-process monotonic
clocks are not comparable) — and observed into ``migration_downtime_ms``.
Bit-exactness across the handoff is a property of the lobby layer: catalog
apps run canonical-depth programs, so the split frame sequence reproduces
the unmigrated checksums exactly (fleet/lobby.py; gated in bench.py's
fleet stage).

Failover reuses the migration tail: workers ship periodic confirmed
checkpoints (fleet/worker.py), so when heartbeats stop the scheduler
already holds a last-confirmed-frame artifact per lobby and re-resumes it
on a surviving worker — ``lobby_migrations_total{outcome="failover"}``.

Metric families (docs/observability.md "Fleet scheduling"):
``fleet_workers``, ``fleet_lobbies_placed_total``,
``lobby_migrations_total{outcome}``, ``admission_rejects_total{reason}``,
``migration_downtime_ms``."""

from __future__ import annotations

import dataclasses
import logging
import socket as _socket
import time
from typing import Dict, List, Optional, Tuple

from .. import telemetry
from ..telemetry.metrics import LATENCY_MS_BUCKETS
from . import protocol as P
from .lobby import LobbySpec, spec_est_bytes
from .observe import FleetObserver

log = logging.getLogger("bevy_ggrs_tpu.fleet.scheduler")

WORKER_TIMEOUT_S = 2.0  # missed heartbeats -> dead -> failover
RESEND_S = 0.5  # control-command (DRAIN/RESUME/PLACE) retry interval
# per-worker device-bytes budget when the worker has not reported one;
# generous for CPU-backed test fleets, deliberately small enough that a
# handful of big lobbies exercises the memory-admission path
DEFAULT_MEM_BUDGET = 512 * 1024 * 1024


@dataclasses.dataclass
class WorkerInfo:
    """Live view of one registered worker (refreshed by heartbeats)."""

    worker_id: str
    addr: Tuple[str, int]
    capacity: int
    last_seen: float
    stats: dict = dataclasses.field(default_factory=dict)
    # canonical digest of ``stats`` — HB_SEQ liveness refreshes must prove
    # they describe the payload we already hold (fleet/protocol.py)
    stats_digest: str = ""

    def lobby_frames(self) -> Dict[str, int]:
        """Per-lobby frames from the latest heartbeat."""
        return {
            lid: int(st.get("frame", 0))
            for lid, st in (self.stats.get("lobbies") or {}).items()
        }

    def qos_floor(self) -> float:
        """Worst reported lobby QoS score (100 when idle)."""
        scores = (self.stats.get("lobby_qos_score") or {}).values()
        return min(scores, default=100.0)

    def device_bytes(self) -> int:
        """Reported device-resident bytes (0 until the first heartbeat)."""
        return int(self.stats.get("device_resident_bytes", 0))


@dataclasses.dataclass
class LobbyRecord:
    """Scheduler-side lifecycle record for one placed lobby."""

    lobby_id: str
    spec: LobbySpec
    worker_id: str
    est_bytes: int
    state: str = "placing"  # placing|running|migrating|failing_over|done
    frame: int = 0
    # latest confirmed checkpoint shipped by the hosting worker
    ckpt_frame: int = -1
    ckpt_blob: Optional[bytes] = None
    # migration in flight: destination worker + barrier + phase
    mig_dst: Optional[str] = None
    mig_barrier: int = -1
    mig_phase: str = ""  # draining | resuming
    mig_t_ckpt: float = 0.0
    last_cmd_sent: float = 0.0
    final_checksum: str = ""
    done_frame: int = -1


class FleetScheduler:
    """Multi-host matchmaker with QoS-aware placement, wire-visible
    admission control, live migration, and heartbeat-timeout failover."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 worker_timeout_s: float = WORKER_TIMEOUT_S,
                 mem_budget_bytes: int = DEFAULT_MEM_BUDGET,
                 observer: Optional[FleetObserver] = None):
        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind((host, port))
        self.worker_timeout_s = worker_timeout_s
        self.mem_budget_bytes = int(mem_budget_bytes)
        self.workers: Dict[str, WorkerInfo] = {}
        self.lobbies: Dict[str, LobbyRecord] = {}
        self._assembler = P.ChunkAssembler()
        # lobby_id -> client addr awaiting SUBMIT_OK/REJECT
        self._submitters: Dict[str, Tuple[str, int]] = {}
        self.events: List[dict] = []  # placement/migration/reject audit log
        # federation read side: heartbeat time-series + SLO burn alerts
        self.observer = observer if observer is not None else FleetObserver()

    @property
    def local_addr(self) -> Tuple[str, int]:
        """The bound (host, port) clients and workers should target."""
        return self._sock.getsockname()

    def close(self) -> None:
        """Release the socket (tests; the CLI just exits)."""
        self._sock.close()

    # -- outbound ----------------------------------------------------------

    def _send(self, data: bytes, addr) -> None:
        try:
            self._sock.sendto(data, addr)
        except OSError:
            pass

    def _send_worker(self, worker_id: str, data: bytes) -> None:
        w = self.workers.get(worker_id)
        if w is not None:
            self._send(data, w.addr)

    def _event(self, kind: str, **fields) -> None:
        self.events.append({"event": kind, **fields})

    def _wire(self, op: str, lid: str = "", worker: str = "",
              frame: int = 0) -> None:
        """Stamp one control-plane wire event onto the timeline's scheduler
        track — the N-way trace merge pairs these with the workers' side
        into flow arrows and clock-alignment anchors (telemetry/trace.py)."""
        telemetry.record("fleet_wire", track="scheduler", op=op, lid=lid,
                         worker=worker, frame=frame)

    # -- placement ---------------------------------------------------------

    def _assigned(self, worker_id: str) -> List[LobbyRecord]:
        return [
            r for r in self.lobbies.values()
            if r.worker_id == worker_id and r.state != "done"
        ]

    def _assigned_bytes(self, worker_id: str) -> int:
        return sum(r.est_bytes for r in self._assigned(worker_id))

    def _choose_worker(
        self, est_bytes: int, exclude: Tuple[str, ...] = ()
    ) -> Tuple[Optional[str], str]:
        """Greedy placement: best feasible worker, or (None, reason).

        Feasibility = free slot AND bytes headroom; score prefers the
        emptiest worker by slot fraction, then the least loaded by assigned
        bytes, then the best QoS floor — a cheap greedy bin-pack over live
        heartbeat state rather than an offline optimum, because workers
        join/die between any two polls anyway."""
        if not self.workers:
            return None, "no_workers"
        best, best_key = None, None
        saw_capacity_full = saw_memory_full = False
        for wid, w in self.workers.items():
            if wid in exclude:
                continue
            used = len(self._assigned(wid))
            if used >= w.capacity:
                saw_capacity_full = True
                continue
            if self._assigned_bytes(wid) + est_bytes > self.mem_budget_bytes:
                saw_memory_full = True
                continue
            key = (
                used / max(1, w.capacity),
                self._assigned_bytes(wid) + w.device_bytes(),
                -w.qos_floor(),
            )
            if best_key is None or key < best_key:
                best, best_key = wid, key
        if best is not None:
            return best, ""
        if saw_memory_full and not saw_capacity_full:
            return None, "memory"
        if saw_capacity_full:
            return None, "capacity"
        return None, "no_workers"

    def submit(self, spec: LobbySpec,
               client_addr: Optional[Tuple[str, int]] = None
               ) -> Tuple[bool, str]:
        """Admit-and-place one lobby (the SUBMIT path, also callable
        in-process).  Returns ``(admitted, worker_or_reason)``; wire
        submitters additionally get SUBMIT_OK / REJECT datagrams."""
        lid = spec.lobby_id
        if lid in self.lobbies and self.lobbies[lid].state != "done":
            reason = "duplicate"
            telemetry.count("admission_rejects_total",
                            help="fleet admissions refused, by reason",
                            reason=reason)
            self._event("reject", lobby=lid, reason=reason)
            if client_addr:
                self._send(P.encode_reject(lid, reason), client_addr)
            return False, reason
        est = spec_est_bytes(spec)
        wid, reason = self._choose_worker(est)
        if wid is None:
            telemetry.count("admission_rejects_total",
                            help="fleet admissions refused, by reason",
                            reason=reason)
            self._event("reject", lobby=lid, reason=reason)
            log.info("reject lobby %s: %s", lid, reason)
            if client_addr:
                self._send(P.encode_reject(lid, reason), client_addr)
            return False, reason
        rec = LobbyRecord(lobby_id=lid, spec=spec, worker_id=wid,
                          est_bytes=est)
        self.lobbies[lid] = rec
        if client_addr:
            self._submitters[lid] = client_addr
        self._place(rec)
        telemetry.count("fleet_lobbies_placed_total",
                        help="lobbies admitted and placed on a worker")
        self._event("place", lobby=lid, worker=wid, est_bytes=est)
        log.info("placed lobby %s on worker %s (est %d bytes)", lid, wid, est)
        return True, wid

    def _place(self, rec: LobbyRecord) -> None:
        rec.state = "placing"
        rec.last_cmd_sent = time.monotonic()
        self._send_worker(
            rec.worker_id, P.encode_place(rec.lobby_id, rec.spec.to_json())
        )
        self._wire("PLACE", rec.lobby_id, rec.worker_id)

    def drop(self, lobby_id: str) -> bool:
        """Tear a lobby down: DROP to its worker, forget the record (frees
        the slot for placement — the bench uses this to release its
        admission-probe filler lobbies)."""
        rec = self.lobbies.pop(lobby_id, None)
        if rec is None:
            return False
        self._send_worker(rec.worker_id, P.encode_drop(lobby_id))
        if rec.mig_dst:
            self._send_worker(rec.mig_dst, P.encode_drop(lobby_id))
        self._submitters.pop(lobby_id, None)
        self._event("drop", lobby=lobby_id, worker=rec.worker_id)
        return True

    # -- migration ---------------------------------------------------------

    def migrate(self, lobby_id: str, dst: Optional[str] = None,
                barrier_margin: int = 32) -> bool:
        """Start a live migration: drain at a confirmed-frame barrier ahead
        of the lobby's last reported frame, then resume on ``dst`` (chosen
        by placement when None).  Returns False (and counts a failed
        migration) when there is nowhere to go."""
        rec = self.lobbies.get(lobby_id)
        if rec is None or rec.state not in ("running", "placing"):
            return False
        if dst is None:
            dst, _reason = self._choose_worker(
                rec.est_bytes, exclude=(rec.worker_id,)
            )
        if dst is None or dst == rec.worker_id or dst not in self.workers:
            telemetry.count("lobby_migrations_total",
                            help="lobby migrations, by outcome",
                            outcome="failed")
            self._event("migrate_failed", lobby=lobby_id, reason="no_dst")
            return False
        rec.state = "migrating"
        rec.mig_dst = dst
        rec.mig_phase = "draining"
        # the barrier must sit at/ahead of the source's true frame; its
        # heartbeat view can lag, so pad by a margin — the worker clamps a
        # stale barrier up to its current frame anyway
        rec.mig_barrier = rec.frame + barrier_margin
        rec.last_cmd_sent = time.monotonic()
        self._send_worker(
            rec.worker_id, P.encode_drain(lobby_id, rec.mig_barrier)
        )
        self._wire("DRAIN", lobby_id, rec.worker_id, rec.mig_barrier)
        self._event("migrate_start", lobby=lobby_id, src=rec.worker_id,
                    dst=dst, barrier=rec.mig_barrier)
        log.info("migrating lobby %s: %s -> %s (barrier %d)",
                 lobby_id, rec.worker_id, dst, rec.mig_barrier)
        return True

    def _ship_resume(self, rec: LobbyRecord) -> None:
        """RESUME order + checkpoint chunks to the destination worker."""
        rec.last_cmd_sent = time.monotonic()
        self._send_worker(rec.mig_dst, P.encode_resume(
            rec.lobby_id, rec.ckpt_frame, rec.spec.to_json()
        ))
        self._wire("RESUME", rec.lobby_id, rec.mig_dst, rec.ckpt_frame)
        for d in P.chunk_checkpoint(rec.lobby_id, rec.ckpt_frame,
                                    rec.ckpt_blob):
            self._send_worker(rec.mig_dst, d)

    def _finish_migration(self, rec: LobbyRecord, resumed_frame: int,
                          now: float) -> None:
        src = rec.worker_id
        downtime_ms = max(0.0, (now - rec.mig_t_ckpt) * 1000.0)
        telemetry.count("lobby_migrations_total",
                        help="lobby migrations, by outcome", outcome="ok")
        telemetry.observe("migration_downtime_ms", downtime_ms,
                          help="ckpt-complete to RESUME_OK, scheduler clock",
                          buckets=LATENCY_MS_BUCKETS)
        self._event("migrate_ok", lobby=rec.lobby_id, src=src,
                    dst=rec.mig_dst, frame=resumed_frame,
                    downtime_ms=round(downtime_ms, 3))
        self.observer.note_migration(rec.lobby_id, downtime_ms, now)
        log.info("migrated lobby %s: %s -> %s at frame %d (%.1f ms down)",
                 rec.lobby_id, src, rec.mig_dst, resumed_frame, downtime_ms)
        self._send_worker(src, P.encode_drop(rec.lobby_id))
        self._wire("DROP", rec.lobby_id, src, resumed_frame)
        rec.worker_id = rec.mig_dst
        rec.state = "running"
        rec.frame = resumed_frame
        rec.mig_dst = None
        rec.mig_phase = ""

    # -- failover ----------------------------------------------------------

    def _failover_worker(self, wid: str) -> None:
        """A worker stopped heartbeating: resume its lobbies elsewhere from
        their last confirmed checkpoints."""
        dead = self.workers.pop(wid, None)
        if dead is None:
            return
        log.warning("worker %s timed out; failing over its lobbies", wid)
        self._event("worker_dead", worker=wid)
        self._wire("FAILOVER", worker=wid)
        self.observer.forget_worker(wid, time.monotonic())
        for rec in list(self.lobbies.values()):
            if rec.worker_id != wid and rec.mig_dst != wid:
                continue
            if rec.state == "done":
                continue
            if rec.mig_dst == wid:  # migration destination died mid-flight
                rec.mig_dst = None
            if rec.ckpt_blob is None:
                # no confirmed checkpoint ever arrived (death before the
                # first ship): the only honest restart is from frame 0
                dst, _ = self._choose_worker(rec.est_bytes, exclude=(wid,))
                outcome = "restart" if dst else "failed"
                telemetry.count("lobby_migrations_total",
                                help="lobby migrations, by outcome",
                                outcome=outcome)
                self._event("failover_" + outcome, lobby=rec.lobby_id,
                            src=wid, dst=dst, frame=0)
                if dst:
                    rec.worker_id = dst
                    self._place(rec)
                continue
            dst, _ = self._choose_worker(rec.est_bytes, exclude=(wid,))
            if dst is None:
                telemetry.count("lobby_migrations_total",
                                help="lobby migrations, by outcome",
                                outcome="failed")
                self._event("failover_failed", lobby=rec.lobby_id, src=wid)
                continue
            rec.state = "failing_over"
            rec.mig_dst = dst
            rec.mig_phase = "resuming"
            rec.mig_t_ckpt = time.monotonic()
            self._ship_resume(rec)
            telemetry.count("lobby_migrations_total",
                            help="lobby migrations, by outcome",
                            outcome="failover")
            self._event("failover", lobby=rec.lobby_id, src=wid, dst=dst,
                        frame=rec.ckpt_frame)
            log.info("failover lobby %s: %s -> %s from confirmed frame %d",
                     rec.lobby_id, wid, dst, rec.ckpt_frame)

    # -- inbound -----------------------------------------------------------

    def _handle(self, msg: P.Msg, addr, now: float) -> None:
        if msg.kind == P.T_REGISTER:
            w = self.workers.get(msg.a)
            if w is None:
                log.info("worker %s registered (capacity %d)", msg.a,
                         msg.total)
                self._event("register", worker=msg.a, capacity=msg.total)
            self.workers[msg.a] = WorkerInfo(
                worker_id=msg.a, addr=addr, capacity=msg.total,
                last_seen=now, stats=w.stats if w else {},
                stats_digest=w.stats_digest if w else "",
            )
            # ack by echoing a heartbeat-shaped no-op? not needed: any
            # PLACE/heartbeat response proves liveness; workers treat any
            # inbound datagram as the register ack, so send a CKPT_ACK
            # no-op would be misleading — instead the first PLACE acks.
        elif msg.kind == P.T_HEARTBEAT:
            w = self.workers.get(msg.a)
            if w is None:  # heartbeat before/instead of REGISTER: adopt
                cap = int((msg.obj or {}).get("capacity", 1))
                w = WorkerInfo(worker_id=msg.a, addr=addr, capacity=cap,
                               last_seen=now)
                self.workers[msg.a] = w
            w.addr = addr
            w.last_seen = now
            w.stats = msg.obj or {}
            w.stats_digest = P.stats_digest(w.stats)
            for lid, frame in w.lobby_frames().items():
                rec = self.lobbies.get(lid)
                if rec is not None and rec.worker_id == msg.a:
                    rec.frame = max(rec.frame, frame)
            self.observer.ingest_heartbeat(
                msg.a, w.stats, now,
                assigned_slots=len(self._assigned(msg.a)),
            )
        elif msg.kind == P.T_HEARTBEAT_SEQ:
            w = self.workers.get(msg.a)
            # liveness refresh iff the digest proves the stats we already
            # hold; unknown workers / stale digests are ignored — the
            # worker's periodic forced full heartbeat re-adopts within
            # FULL_HEARTBEAT_EVERY beats (fleet/worker.py)
            if w is not None and msg.b == w.stats_digest:
                w.addr = addr
                w.last_seen = now
                self.observer.ingest_liveness(msg.a, now)
        elif msg.kind == P.T_PLACE_OK:
            rec = self.lobbies.get(msg.a)
            if rec is not None and rec.state == "placing":
                rec.state = "running"
                rec.frame = max(rec.frame, msg.frame)
                caddr = self._submitters.pop(msg.a, None)
                if caddr:
                    self._send(
                        P.encode_submit_ok(msg.a, rec.worker_id), caddr
                    )
        elif msg.kind == P.T_CKPT:
            self._on_ckpt_chunk(msg, now)
        elif msg.kind == P.T_RESUME_OK:
            rec = self.lobbies.get(msg.a)
            # mig_dst can be None if the destination died mid-resume and no
            # replacement existed yet; a late RESUME_OK must not complete
            # the handoff to nowhere — the retry loop re-picks a dst
            if (rec is not None and rec.mig_phase == "resuming"
                    and rec.mig_dst is not None):
                if rec.state == "failing_over":
                    # failover downtime is dominated by the timeout window,
                    # not the resume — keep the histogram for migrations
                    self._event("failover_ok", lobby=msg.a,
                                dst=rec.mig_dst, frame=msg.frame)
                    self._send_worker(rec.worker_id, P.encode_drop(msg.a))
                    rec.worker_id = rec.mig_dst
                    rec.state = "running"
                    rec.frame = msg.frame
                    rec.mig_dst = None
                    rec.mig_phase = ""
                else:
                    self._finish_migration(rec, msg.frame, now)
        elif msg.kind == P.T_SUBMIT:
            spec = LobbySpec.from_json(msg.obj)
            if spec.lobby_id != msg.a:
                spec = dataclasses.replace(spec, lobby_id=msg.a)
            self.submit(spec, client_addr=addr)
        elif msg.kind == P.T_DONE:
            rec = self.lobbies.get(msg.a)
            # workers re-announce DONE at heartbeat cadence (loss
            # tolerance): record the audit event on the transition only
            if rec is not None and rec.state != "done":
                rec.state = "done"
                rec.frame = msg.frame
                rec.done_frame = msg.frame
                rec.final_checksum = msg.b
                self._event("done", lobby=msg.a, frame=msg.frame,
                            checksum=msg.b)

    def _on_ckpt_chunk(self, msg: P.Msg, now: float) -> None:
        rec = self.lobbies.get(msg.a)
        if rec is None:
            return
        blob = self._assembler.offer(msg)
        # ack per-chunk-completion only: one ack per completed (lobby,
        # frame) keeps re-ship traffic bounded without per-chunk acks
        if blob is None:
            return
        self._send_worker(rec.worker_id, P.encode_ckpt_ack(msg.a, msg.frame))
        if msg.frame >= rec.ckpt_frame:
            rec.ckpt_frame = msg.frame
            rec.ckpt_blob = blob
        if (rec.state == "migrating" and rec.mig_phase == "draining"
                and msg.frame >= rec.mig_barrier):
            # the barrier checkpoint is in hand: downtime clock starts now
            rec.mig_t_ckpt = now
            rec.mig_phase = "resuming"
            # the CKPT instant anchors the downtime-spanning flow arrow
            # (CKPT -> destination RESUME_OK) in merged fleet traces
            self._wire("CKPT", rec.lobby_id, rec.worker_id, msg.frame)
            self._ship_resume(rec)

    # -- main loop ---------------------------------------------------------

    def _retries(self, now: float) -> None:
        for rec in self.lobbies.values():
            if now - rec.last_cmd_sent < RESEND_S:
                continue
            if rec.state == "placing":
                self._place(rec)
            elif rec.state == "migrating" and rec.mig_phase == "draining":
                rec.last_cmd_sent = now
                self._send_worker(
                    rec.worker_id,
                    P.encode_drain(rec.lobby_id, rec.mig_barrier),
                )
            elif rec.mig_phase == "resuming":
                if rec.mig_dst is None:
                    # the destination died mid-resume and no replacement was
                    # available at failover time: keep trying as workers
                    # (re-)appear
                    dst, _ = self._choose_worker(rec.est_bytes)
                    if dst is None:
                        continue
                    rec.mig_dst = dst
                self._ship_resume(rec)

    def poll(self) -> None:
        """One control quantum: drain the socket, detect dead workers and
        fail their lobbies over, re-send unacked commands, refresh gauges."""
        while True:
            try:
                data, addr = self._sock.recvfrom(65536)
            except (BlockingIOError, OSError):
                break
            msg = P.decode(data)
            if msg is not None:
                self._handle(msg, addr, time.monotonic())
            else:
                P.note_malformed(addr)
        now = time.monotonic()
        for wid, w in list(self.workers.items()):
            if now - w.last_seen > self.worker_timeout_s:
                self._failover_worker(wid)
        self._retries(now)
        telemetry.gauge_set("fleet_workers", len(self.workers),
                            help="live registered fleet workers")
        # throttled SLO evaluation + /fleet topology refresh (the observer
        # no-ops until its eval interval elapses)
        self.observer.tick(now, topology=self.snapshot)

    def run(self, duration_s: Optional[float] = None,
            idle_sleep_s: float = 0.005) -> None:
        """Poll until ``duration_s`` elapses (forever when None) — the
        ``scripts/fleet_scheduler.py`` main loop."""
        t0 = time.monotonic()
        while duration_s is None or time.monotonic() - t0 < duration_s:
            self.poll()
            time.sleep(idle_sleep_s)

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able fleet state: workers, lobbies, audit events (bench
        stage + scripts/fleet_scheduler.py --status)."""
        return {
            "workers": {
                wid: {
                    "capacity": w.capacity,
                    "assigned": len(self._assigned(wid)),
                    "assigned_bytes": self._assigned_bytes(wid),
                    "qos_floor": w.qos_floor(),
                    "device_resident_bytes": w.device_bytes(),
                }
                for wid, w in self.workers.items()
            },
            "lobbies": {
                lid: {
                    "worker": r.worker_id,
                    "state": r.state,
                    "frame": r.frame,
                    "ckpt_frame": r.ckpt_frame,
                    "final_checksum": r.final_checksum,
                }
                for lid, r in self.lobbies.items()
            },
            "events": list(self.events),
        }

    def fleet_snapshot(self, tail: int = 32) -> dict:
        """The federated ``/fleet`` JSON (fleet/observe.py schema): refresh
        the observer's topology from this thread's live state, then return
        its snapshot.  HTTP handler threads must NOT call this — they use
        ``observer.fleet_snapshot()`` directly (topology is refreshed by
        :meth:`poll` at the observer's eval cadence), because only the poll
        thread may read ``self.workers`` / ``self.lobbies``."""
        self.observer.set_topology(self.snapshot())
        return self.observer.fleet_snapshot(tail=tail)


class FleetClient:
    """Wire client for SUBMIT: asks the scheduler to place a lobby and
    reports the wire-visible verdict (the admission-control test surface)."""

    def __init__(self, scheduler_addr: Tuple[str, int]):
        self.scheduler_addr = scheduler_addr
        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind(("127.0.0.1", 0))
        self.last_reject: str = ""

    def close(self) -> None:
        """Release the socket."""
        self._sock.close()

    def submit(self, spec: LobbySpec, timeout_s: float = 5.0,
               resend_s: float = 0.25) -> Optional[str]:
        """Submit ``spec``; block (bounded) for the verdict.  Returns the
        hosting worker_id on SUBMIT_OK, None on REJECT (reason in
        :attr:`last_reject`) or timeout (``last_reject == "timeout"``)."""
        self.last_reject = ""
        deadline = time.monotonic() + timeout_s
        next_send = 0.0
        while time.monotonic() < deadline:
            now = time.monotonic()
            if now >= next_send:
                next_send = now + resend_s
                try:
                    self._sock.sendto(
                        P.encode_submit(spec.lobby_id, spec.to_json()),
                        self.scheduler_addr,
                    )
                except OSError:
                    pass
            try:
                data, addr = self._sock.recvfrom(65536)
            except (BlockingIOError, OSError):
                time.sleep(0.01)
                continue
            msg = P.decode(data)
            if msg is None:
                P.note_malformed(addr)
                continue
            if msg.a != spec.lobby_id:
                continue
            if msg.kind == P.T_SUBMIT_OK:
                return msg.b
            if msg.kind == P.T_REJECT:
                self.last_reject = msg.b
                return None
        self.last_reject = "timeout"
        return None
