"""Fleet-wide telemetry federation: scheduler-side time-series + SLO alerts.

The workers already report rich heartbeat stats (capacity, per-lobby
frames, ``shard_imbalance_ratio``, ``lobby_qos_score``,
``device_resident_bytes`` — fleet/worker.py ``_stats``), but until this
module the scheduler consumed them for placement and dropped them on the
floor.  :class:`FleetObserver` is the read side the ROADMAP-item-1
rebalancer will subscribe to:

- **Time-series rings** — every heartbeat appends bounded ``(t, value)``
  samples per worker (QoS floor, imbalance, device bytes, assigned slots,
  heartbeat gap) and per lobby (QoS, frame), queryable with
  :meth:`SeriesRing.window` / :meth:`SeriesRing.rate`.  The same ingest
  refreshes ``worker=`` / ``lobby=`` labeled gauges on the default
  registry, so a single scheduler-side ``/metrics`` scrape federates the
  whole fleet's load signals.
- **SLO engine** — declarative objectives (:class:`SLO`): a per-lobby QoS
  floor with burn-rate evaluation over a sliding window, a
  migration-downtime ceiling, and per-worker heartbeat liveness.  Breaches
  must SUSTAIN for ``burn_window_s`` before an alert fires (one bad sample
  is not an incident) and must stay clean for ``resolve_window_s`` before
  it resolves (hysteresis); fire/resolve transitions are deduplicated
  per ``(slo, subject)``, appended as typed :class:`AlertEvent` records,
  counted into ``fleet_alerts_total{slo,state}``, and stamped onto the
  timeline as ``fleet_alert`` instants (visible in merged fleet traces —
  telemetry/trace.py).
- **HTTP federation** — :func:`fleet_routes` / :func:`start_fleet_exporter`
  extend the Prometheus exporter with ``/fleet`` (topology + series
  snapshot + alerts, the one schema the scheduler CLI also prints) and a
  fleet-wide ``/qos`` (worst-N lobbies across every worker).

Threading: the exporter serves ``/fleet`` and ``/qos`` from HTTP handler
threads while the scheduler's poll loop ingests, so every public method
takes ``self._lock``; metric/timeline emission happens strictly OUTSIDE
the lock (the registry has its own lock, and alert side-effects are
computed as a transition list first).  BGT060 covers this module via
``CONCURRENCY_MODULES`` + ``THREAD_ROOTS`` (scripts/lint/config.py).

See docs/observability.md "Fleet federation & SLOs" for the metric rows
and snapshot schemas."""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from threading import Lock
from typing import Callable, Dict, List, Optional, Tuple

from .. import telemetry

FLEET_SCHEMA = "fleet/v1"
FLEET_QOS_SCHEMA = "fleet-qos/v1"

#: default ring capacity per series — at the 0.25 s heartbeat cadence this
#: holds ~64 s of history per worker, enough for any burn window in use
SERIES_CAPACITY = 256

#: alert history bound (active alerts live in a separate dict)
ALERT_HISTORY = 512


class SeriesRing:
    """Bounded ``(t, value)`` time-series ring with window/rate queries."""

    def __init__(self, capacity: int = SERIES_CAPACITY):
        self._data: deque = deque(maxlen=int(capacity))

    def __len__(self) -> int:
        return len(self._data)

    def add(self, t: float, v: float) -> None:
        """Append one sample (monotonic ``t`` expected, not enforced)."""
        self._data.append((float(t), float(v)))

    def last(self) -> Optional[Tuple[float, float]]:
        """The newest ``(t, value)`` sample, or None when empty."""
        return self._data[-1] if self._data else None

    def window(self, span_s: float,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Samples with ``t >= now - span_s`` (oldest first)."""
        if not self._data:
            return []
        ref = self._data[-1][0] if now is None else now
        lo = ref - span_s
        return [(t, v) for t, v in self._data if t >= lo]

    def rate(self, span_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second delta over the window (counter-style series); None
        when fewer than two samples span a non-zero interval."""
        win = self.window(span_s, now)
        if len(win) < 2:
            return None
        dt = win[-1][0] - win[0][0]
        if dt <= 0:
            return None
        return (win[-1][1] - win[0][1]) / dt

    def tail(self, n: int) -> List[List[float]]:
        """The newest ``n`` samples as JSON-able ``[t, value]`` pairs."""
        items = list(self._data)[-int(n):]
        return [[t, v] for t, v in items]


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative objective evaluated per subject (lobby or worker).

    ``signal`` selects the breach predicate:

    - ``"qos_floor"`` — per-lobby; a sample breaches when the lobby's QoS
      score drops BELOW ``threshold``; the burn-rate test requires at
      least ``burn_fraction`` of the samples inside ``burn_window_s`` to
      breach, continuously for the whole window, before firing.
    - ``"migration_downtime"`` — per-lobby; a migration/failover downtime
      event ABOVE ``threshold`` (ms) breaches; discrete events fire
      immediately (``burn_window_s`` is ignored — one blown ceiling IS
      the incident) and age out of breach after ``resolve_window_s``.
    - ``"heartbeat_liveness"`` — per-worker; breaches while the gap since
      the last accepted heartbeat exceeds ``threshold`` (s); the gap
      itself is the sustain, so fires as soon as it is observed.
    """

    slo_id: str
    signal: str
    threshold: float
    burn_window_s: float = 1.0
    resolve_window_s: float = 1.0
    burn_fraction: float = 1.0
    subject: Optional[str] = None  # pin to one lobby/worker (None = all)


@dataclasses.dataclass(frozen=True)
class AlertEvent:
    """One fire/resolve transition emitted by :meth:`FleetObserver.evaluate`."""

    slo_id: str
    subject: str
    state: str  # "fire" | "resolve"
    t: float
    value: Optional[float]
    threshold: float
    signal: str


def default_slos(*, qos_floor: float = 50.0, qos_burn_window_s: float = 1.0,
                 downtime_ceiling_ms: float = 2000.0,
                 liveness_gap_s: float = 1.5,
                 resolve_window_s: float = 1.0) -> List[SLO]:
    """The stock objective set the scheduler installs when none is given."""
    return [
        SLO("qos_floor", "qos_floor", qos_floor,
            burn_window_s=qos_burn_window_s,
            resolve_window_s=resolve_window_s),
        SLO("migration_downtime", "migration_downtime", downtime_ceiling_ms,
            burn_window_s=0.0, resolve_window_s=resolve_window_s),
        SLO("heartbeat_liveness", "heartbeat_liveness", liveness_gap_s,
            burn_window_s=0.0, resolve_window_s=resolve_window_s),
    ]


class _AlertState:
    """Per-(slo, subject) dedup/hysteresis state."""

    __slots__ = ("active", "breach_since", "clear_since")

    def __init__(self):
        self.active = False
        self.breach_since: Optional[float] = None
        self.clear_since: Optional[float] = None


_WORKER_GAUGES = (
    # (series key, gauge name) — refreshed per accepted full heartbeat
    ("qos_floor", "fleet_worker_qos_floor"),
    ("imbalance", "fleet_worker_imbalance_ratio"),
    ("device_bytes", "fleet_worker_device_resident_bytes"),
    ("assigned_slots", "fleet_worker_assigned_slots"),
    ("heartbeat_gap_ms", "fleet_worker_heartbeat_gap_ms"),
)


class FleetObserver:
    """Scheduler-side federation point: heartbeat time-series, SLO burn
    alerts, and the ``/fleet`` + fleet-wide ``/qos`` snapshot schemas."""

    def __init__(self, slos: Optional[List[SLO]] = None,
                 series_capacity: int = SERIES_CAPACITY,
                 eval_interval_s: float = 0.05):
        self._lock = Lock()
        self._slos: List[SLO] = list(slos) if slos is not None \
            else default_slos()
        self._capacity = int(series_capacity)
        self.eval_interval_s = float(eval_interval_s)
        self._worker_series: Dict[str, Dict[str, SeriesRing]] = {}
        self._lobby_series: Dict[str, Dict[str, SeriesRing]] = {}
        self._lobby_worker: Dict[str, str] = {}
        self._last_hb: Dict[str, float] = {}
        self._astate: Dict[Tuple[str, str], _AlertState] = {}
        self._active: Dict[Tuple[str, str], AlertEvent] = {}
        self._alerts: List[AlertEvent] = []
        self._topology: dict = {}
        self._last_eval = float("-inf")

    # -- ingest ------------------------------------------------------------

    def _series_locked(self, table: Dict[str, Dict[str, SeriesRing]],
                       key: str) -> Dict[str, SeriesRing]:
        d = table.get(key)
        if d is None:
            d = {}
            table[key] = d
        return d

    def _ring_locked(self, d: Dict[str, SeriesRing], key: str) -> SeriesRing:
        r = d.get(key)
        if r is None:
            r = SeriesRing(self._capacity)
            d[key] = r
        return r

    def ingest_heartbeat(self, worker_id: str, stats: dict,
                         now: Optional[float] = None,
                         assigned_slots: Optional[int] = None) -> None:
        """Fold one full heartbeat into the rings + federation gauges."""
        now = time.monotonic() if now is None else now
        stats = stats or {}
        lobbies = stats.get("lobbies") or {}
        qos_map = stats.get("lobby_qos_score") or {}
        qos_floor = float(min(qos_map.values(), default=100.0))
        imbalance = float(stats.get("shard_imbalance_ratio", 1.0))
        dev_bytes = int(stats.get("device_resident_bytes", 0))
        with self._lock:
            prev = self._last_hb.get(worker_id)
            gap_ms = (now - prev) * 1000.0 if prev is not None else 0.0
            self._last_hb[worker_id] = now
            ws = self._series_locked(self._worker_series, worker_id)
            self._ring_locked(ws, "qos_floor").add(now, qos_floor)
            self._ring_locked(ws, "imbalance").add(now, imbalance)
            self._ring_locked(ws, "device_bytes").add(now, dev_bytes)
            if assigned_slots is not None:
                self._ring_locked(ws, "assigned_slots").add(
                    now, int(assigned_slots))
            self._ring_locked(ws, "heartbeat_gap_ms").add(now, gap_ms)
            for lid, st in lobbies.items():
                ls = self._series_locked(self._lobby_series, lid)
                self._ring_locked(ls, "frame").add(
                    now, int(st.get("frame", 0)))
                self._ring_locked(ls, "qos").add(
                    now, float(qos_map.get(lid, 100.0)))
                self._lobby_worker[lid] = worker_id
        # gauge refresh outside the observer lock (registry has its own)
        telemetry.gauge_set("fleet_worker_qos_floor", qos_floor,
                            help="worst reported lobby QoS per worker",
                            worker=worker_id)
        telemetry.gauge_set("fleet_worker_imbalance_ratio", imbalance,
                            help="reported shard_imbalance_ratio per worker",
                            worker=worker_id)
        telemetry.gauge_set("fleet_worker_device_resident_bytes", dev_bytes,
                            help="reported device-resident bytes per worker",
                            worker=worker_id)
        if assigned_slots is not None:
            telemetry.gauge_set("fleet_worker_assigned_slots",
                                int(assigned_slots),
                                help="scheduler-side assigned lobby slots",
                                worker=worker_id)
        telemetry.gauge_set("fleet_worker_heartbeat_gap_ms", gap_ms,
                            help="gap between accepted heartbeats per worker",
                            worker=worker_id)
        for lid in lobbies:
            telemetry.gauge_set("fleet_lobby_qos_score",
                                float(qos_map.get(lid, 100.0)),
                                help="per-lobby QoS score, federated at the "
                                     "scheduler", lobby=lid, worker=worker_id)

    def ingest_liveness(self, worker_id: str,
                        now: Optional[float] = None) -> None:
        """Refresh liveness only (digest-suppressed seq heartbeat)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            prev = self._last_hb.get(worker_id)
            gap_ms = (now - prev) * 1000.0 if prev is not None else 0.0
            self._last_hb[worker_id] = now
            ws = self._series_locked(self._worker_series, worker_id)
            self._ring_locked(ws, "heartbeat_gap_ms").add(now, gap_ms)

    def note_migration(self, lobby_id: str, downtime_ms: float,
                       now: Optional[float] = None) -> None:
        """Record one migration/failover downtime event for ``lobby_id``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            ls = self._series_locked(self._lobby_series, lobby_id)
            self._ring_locked(ls, "downtime_ms").add(now, float(downtime_ms))

    def forget_worker(self, worker_id: str,
                      now: Optional[float] = None) -> List[AlertEvent]:
        """Drop a dead/removed worker; force-resolve its active alerts so
        a failed-over worker does not alert forever."""
        now = time.monotonic() if now is None else now
        emitted: List[AlertEvent] = []
        with self._lock:
            self._last_hb.pop(worker_id, None)
            self._worker_series.pop(worker_id, None)
            for key in [k for k in self._astate if k[1] == worker_id]:
                st = self._astate.pop(key)
                if st.active:
                    prev = self._active.pop(key, None)
                    ev = AlertEvent(
                        slo_id=key[0], subject=worker_id, state="resolve",
                        t=now, value=None,
                        threshold=prev.threshold if prev else 0.0,
                        signal=prev.signal if prev else "")
                    self._alerts.append(ev)
                    emitted.append(ev)
            del self._alerts[:-ALERT_HISTORY]
        self._emit(emitted)
        return emitted

    def set_topology(self, topology: dict) -> None:
        """Install the scheduler's latest workers/lobbies/events view (the
        topology half of the ``/fleet`` snapshot)."""
        with self._lock:
            self._topology = topology or {}

    # -- SLO evaluation ----------------------------------------------------

    def _subjects_locked(self, slo: SLO) -> List[str]:
        if slo.subject is not None:
            return [slo.subject]
        if slo.signal == "heartbeat_liveness":
            return list(self._last_hb)
        key = "downtime_ms" if slo.signal == "migration_downtime" else "qos"
        return [lid for lid, d in self._lobby_series.items() if key in d]

    def _breach_locked(self, slo: SLO, subject: str,
                       now: float) -> Tuple[bool, Optional[float]]:
        """(breaching-now, observed value) for one (slo, subject)."""
        if slo.signal == "heartbeat_liveness":
            last = self._last_hb.get(subject)
            if last is None:
                return False, None
            gap = now - last
            return gap > slo.threshold, round(gap, 6)
        series = self._lobby_series.get(subject, {})
        if slo.signal == "qos_floor":
            ring = series.get("qos")
            win = ring.window(slo.burn_window_s, now) if ring else []
            if not win:
                return False, None
            bad = sum(1 for _, v in win if v < slo.threshold)
            return bad / len(win) >= slo.burn_fraction, win[-1][1]
        if slo.signal == "migration_downtime":
            ring = series.get("downtime_ms")
            win = ring.window(slo.resolve_window_s, now) if ring else []
            bad = [v for _, v in win if v > slo.threshold]
            if bad:
                return True, max(bad)
            return False, (win[-1][1] if win else None)
        return False, None

    def evaluate(self, now: Optional[float] = None) -> List[AlertEvent]:
        """One evaluation tick over every (slo, subject): fire sustained
        breaches, resolve with hysteresis, dedup across ticks.  Returns the
        transitions emitted THIS tick (usually empty)."""
        now = time.monotonic() if now is None else now
        emitted: List[AlertEvent] = []
        with self._lock:
            self._last_eval = now
            for slo in self._slos:
                for subject in self._subjects_locked(slo):
                    key = (slo.slo_id, subject)
                    st = self._astate.get(key)
                    if st is None:
                        st = _AlertState()
                        self._astate[key] = st
                    breaching, value = self._breach_locked(slo, subject, now)
                    if not st.active:
                        if not breaching:
                            st.breach_since = None
                            continue
                        if st.breach_since is None:
                            st.breach_since = now
                        if now - st.breach_since >= slo.burn_window_s:
                            st.active = True
                            st.breach_since = None
                            st.clear_since = None
                            ev = AlertEvent(slo.slo_id, subject, "fire", now,
                                            value, slo.threshold, slo.signal)
                            self._active[key] = ev
                            self._alerts.append(ev)
                            emitted.append(ev)
                    elif breaching:
                        st.clear_since = None
                    else:
                        if st.clear_since is None:
                            st.clear_since = now
                        if now - st.clear_since >= slo.resolve_window_s:
                            st.active = False
                            st.clear_since = None
                            self._active.pop(key, None)
                            ev = AlertEvent(slo.slo_id, subject, "resolve",
                                            now, value, slo.threshold,
                                            slo.signal)
                            self._alerts.append(ev)
                            emitted.append(ev)
            del self._alerts[:-ALERT_HISTORY]
        self._emit(emitted)
        return emitted

    def tick(self, now: Optional[float] = None,
             topology: Optional[Callable[[], dict]] = None
             ) -> List[AlertEvent]:
        """Throttled per-poll hook: refresh topology + evaluate at most
        every ``eval_interval_s`` (the scheduler calls this every poll)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            due = now - self._last_eval >= self.eval_interval_s
        if not due:
            return []
        if topology is not None:
            self.set_topology(topology())
        return self.evaluate(now)

    def _emit(self, events: List[AlertEvent]) -> None:
        """Alert side-effects — strictly outside :attr:`_lock`."""
        for ev in events:
            telemetry.count(
                "fleet_alerts_total",
                help="SLO alert transitions at the fleet scheduler",
                slo=ev.slo_id, state=ev.state,
            )
            telemetry.record(
                "fleet_alert", track="scheduler", slo=ev.slo_id,
                subject=ev.subject, state=ev.state, value=ev.value,
                threshold=ev.threshold,
            )

    # -- read side (HTTP handler threads + CLI) ----------------------------

    def window(self, scope: str, key: str, series: str, span_s: float,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Windowed samples for one series; ``scope`` is ``"worker"`` or
        ``"lobby"`` (the rebalancer-facing query surface)."""
        table = self._worker_series if scope == "worker" \
            else self._lobby_series
        with self._lock:
            ring = table.get(key, {}).get(series)
            return ring.window(span_s, now) if ring else []

    def rate(self, scope: str, key: str, series: str, span_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Windowed per-second delta for one series (see :meth:`window`)."""
        table = self._worker_series if scope == "worker" \
            else self._lobby_series
        with self._lock:
            ring = table.get(key, {}).get(series)
            return ring.rate(span_s, now) if ring else None

    def active_alerts(self) -> List[dict]:
        """Currently-firing alerts as JSON-able dicts."""
        with self._lock:
            return [dataclasses.asdict(e) for e in self._active.values()]

    def alert_history(self, n: int = ALERT_HISTORY) -> List[dict]:
        """The newest ``n`` fire/resolve transitions, oldest first."""
        with self._lock:
            return [dataclasses.asdict(e) for e in self._alerts[-n:]]

    def fleet_snapshot(self, now: Optional[float] = None,
                       tail: int = 32) -> dict:
        """The ``/fleet`` JSON: topology + per-entity series tails + alerts
        + audit-event tail.  One schema for HTTP and the CLI."""
        now = time.monotonic() if now is None else now
        with self._lock:
            workers: Dict[str, dict] = {}
            topo_workers = self._topology.get("workers") or {}
            for wid, series in self._worker_series.items():
                row = dict(topo_workers.get(wid) or {})
                last = self._last_hb.get(wid)
                row["heartbeat_gap_s"] = (
                    round(now - last, 6) if last is not None else None
                )
                row["series"] = {k: r.tail(tail) for k, r in series.items()}
                workers[wid] = row
            for wid, row in topo_workers.items():
                workers.setdefault(wid, dict(row))
            lobbies: Dict[str, dict] = {}
            topo_lobbies = self._topology.get("lobbies") or {}
            for lid, series in self._lobby_series.items():
                row = dict(topo_lobbies.get(lid) or {})
                row.setdefault("worker", self._lobby_worker.get(lid, ""))
                row["series"] = {k: r.tail(tail) for k, r in series.items()}
                lobbies[lid] = row
            for lid, row in topo_lobbies.items():
                lobbies.setdefault(lid, dict(row))
            return {
                "schema": FLEET_SCHEMA,
                "t": now,
                "workers": workers,
                "lobbies": lobbies,
                "alerts": {
                    "active": [dataclasses.asdict(e)
                               for e in self._active.values()],
                    "recent": [dataclasses.asdict(e)
                               for e in self._alerts[-tail:]],
                },
                "events": list(self._topology.get("events") or [])[-tail:],
            }

    def fleet_qos(self, n: int = 10) -> dict:
        """Fleet-wide worst-N lobbies by latest QoS sample (the fleet-level
        ``/qos`` payload — one scrape ranks every lobby on every worker)."""
        with self._lock:
            rows = []
            for lid, series in self._lobby_series.items():
                ring = series.get("qos")
                last = ring.last() if ring else None
                if last is None:
                    continue
                rows.append({
                    "lobby": lid,
                    "worker": self._lobby_worker.get(lid, ""),
                    "t": last[0],
                    "qos": last[1],
                })
            rows.sort(key=lambda r: (r["qos"], r["lobby"]))
            active = [dataclasses.asdict(e) for e in self._active.values()]
        return {
            "schema": FLEET_QOS_SCHEMA,
            "worst_lobbies": rows[:int(n)],
            "active_alerts": active,
        }


def fleet_routes(observer: FleetObserver,
                 worst_n: int = 10) -> Dict[str, Callable[[], dict]]:
    """Extra JSON routes for the metrics exporter: ``/fleet`` and the
    fleet-wide ``/qos`` override (both served from handler threads)."""
    return {
        "/fleet": observer.fleet_snapshot,
        "/qos": lambda: observer.fleet_qos(worst_n),
    }


def start_fleet_exporter(observer: FleetObserver, port: int = 0,
                         host: str = "127.0.0.1", registry=None,
                         worst_n: int = 10):
    """Start the scheduler's HTTP exporter: federated ``/metrics`` (the
    ``worker=`` labeled gauges live on the default registry) plus
    ``/fleet`` and fleet-wide ``/qos`` from :func:`fleet_routes`."""
    from ..telemetry.prometheus import start_http_exporter

    return start_http_exporter(
        port=port, host=host, registry=registry,
        extra_json_routes=fleet_routes(observer, worst_n),
    )
