"""Fleet control-plane wire protocol — riding the room wire format.

The fleet messages reuse the room server's framing verbatim (same
``ROOM_MAGIC`` header struct, length-prefixed UTF-8 strings, bail-on-
malformed decoding posture — see session/room.py) so a fleet scheduler can
share ports, parsers, and packet-capture tooling with the signaling plane
it grew out of.  Type bytes 32+ are the fleet range; the room server drops
unknown types on the floor, so the planes can even cohabit one socket.

Message inventory (w = worker, s = scheduler, c = client):

========  =======  ====================================================
type      dir      payload
========  =======  ====================================================
REGISTER  w -> s   worker_id, capacity (u16) — (re-)announce a worker
HEARTBEAT w -> s   worker_id, JSON stats (lobbies, qos, bytes, ratio)
HB_SEQ    w -> s   worker_id, seq (u32), stats digest — liveness-only
                   heartbeat when the stats payload is unchanged (the
                   scheduler refreshes last-seen iff the digest matches
                   the stats it already holds)
PLACE     s -> w   lobby_id, JSON LobbySpec — host this lobby from 0
PLACE_OK  w -> s   lobby_id, frame (u32) — lobby is running
DRAIN     s -> w   lobby_id, barrier frame (u32) — stop AT barrier,
                   checkpoint, ship (the migration drain half)
CKPT      both     lobby_id, frame (u32), seq/total (u16), chunk bytes
                   — chunked checkpoint transfer, reassembled by (lobby,
                   frame); fits any checkpoint through UDP datagrams
CKPT_ACK  s -> w   lobby_id, frame (u32) — stop re-shipping this one
RESUME    s -> w   lobby_id, frame (u32), JSON LobbySpec — expect CKPT
                   chunks for (lobby, frame), restore, run
RESUME_OK w -> s   lobby_id, frame (u32) — restored and running
DROP      s -> w   lobby_id — forget a drained/migrated-away lobby
SUBMIT    c -> s   lobby_id, JSON LobbySpec — request placement
SUBMIT_OK s -> c   lobby_id, worker_id — admitted and placed
REJECT    s -> c   lobby_id, reason — admission refused (wire-visible
                   reason; the room server's join-reject type, reused)
DONE      w -> s   lobby_id, frame (u32), checksum (hex str)
========  =======  ====================================================

Stats/spec payloads are JSON: the control plane is low-rate (heartbeats,
placements), so self-describing beats packed here — the data plane (game
datagrams, checkpoint chunks) stays binary.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import struct
from typing import Any, List, Optional, Tuple

from ..session.room import ROOM_MAGIC, _HDR, _Reader, _pack_str

log = logging.getLogger(__name__)

# peers we have already warned about — the counter keeps counting, the
# log line fires once per peer so a hostile/misconfigured sender cannot
# flood the scheduler's log at datagram rate
_malformed_peers: set = set()


def note_malformed(addr=None) -> None:
    """Account one datagram :func:`decode` dropped (wrong magic, truncated
    fields, bad JSON, unknown type byte — ANY failed decode counts) into
    ``fleet_malformed_datagrams_total`` and warn once per peer.

    Callers pass the ``recvfrom`` address when they have it; a ``None``
    peer is grouped under ``<unknown>``."""
    from .. import telemetry

    telemetry.count(
        "fleet_malformed_datagrams_total",
        help="fleet datagrams dropped by the decoder as malformed or "
             "non-fleet (any failed decode, unknown type bytes included)",
    )
    peer = (
        f"{addr[0]}:{addr[1]}"
        if isinstance(addr, tuple) and len(addr) >= 2
        else "<unknown>"
    )
    if peer not in _malformed_peers:
        _malformed_peers.add(peer)
        log.warning(
            "fleet: dropping malformed datagram(s) from %s (counted in "
            "fleet_malformed_datagrams_total)", peer,
        )

# fleet message range: 32+ (room control types are 1..8)
T_REGISTER = 32
T_HEARTBEAT = 33
T_PLACE = 34
T_PLACE_OK = 35
T_DRAIN = 36
T_CKPT = 37
T_CKPT_ACK = 38
T_RESUME = 39
T_RESUME_OK = 40
T_DROP = 41
T_SUBMIT = 42
T_SUBMIT_OK = 43
T_DONE = 44
T_HEARTBEAT_SEQ = 45
# admission rejects reuse the room server's reject type so a fleet client
# shares the room client's "refused, here is why" handling
T_REJECT = 8

# checkpoint chunk payload size: comfortably under the 65507-byte UDP
# datagram ceiling with header + ids on top, large enough that a small
# lobby ships in a handful of datagrams
CKPT_CHUNK_BYTES = 32 * 1024


def _pack_u32(v: int) -> bytes:
    return struct.pack("<I", int(v) & 0xFFFFFFFF)


def _pack_u16(v: int) -> bytes:
    return struct.pack("<H", int(v) & 0xFFFF)


def _u32(r: _Reader) -> int:
    d = r.take(4)
    return struct.unpack("<I", d)[0] if r.ok else 0


def _json_str(obj: Any) -> str:
    return json.dumps(obj, separators=(",", ":"), sort_keys=True)


def _pack_json(obj: Any) -> bytes:
    """JSON payloads ride as the datagram tail (no length prefix needed —
    they are always the final field)."""
    return _json_str(obj).encode("utf-8")


def _read_json(r: _Reader) -> Optional[Any]:
    raw = r.rest()
    if not r.ok:
        return None
    try:
        return json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        return None


@dataclasses.dataclass(frozen=True)
class Msg:
    """One decoded fleet datagram.  ``kind`` is the ``T_*`` type byte;
    unused fields stay at their defaults (the decoder only fills what the
    type carries)."""

    kind: int
    a: str = ""          # worker_id / lobby_id (first id field)
    b: str = ""          # second id / reason / checksum-hex
    frame: int = 0
    seq: int = 0
    total: int = 0
    blob: bytes = b""    # checkpoint chunk payload
    obj: Any = None      # decoded JSON payload (stats / spec)


def encode_register(worker_id: str, capacity: int) -> bytes:
    """REGISTER: announce a worker and its lobby capacity."""
    return (_HDR.pack(ROOM_MAGIC, T_REGISTER) + _pack_str(worker_id)
            + _pack_u16(capacity))


def encode_heartbeat(worker_id: str, stats: dict) -> bytes:
    """HEARTBEAT: the worker's live load/QoS report (JSON tail)."""
    return (_HDR.pack(ROOM_MAGIC, T_HEARTBEAT) + _pack_str(worker_id)
            + _pack_json(stats))


def stats_digest(stats: dict) -> str:
    """Canonical digest of a heartbeat stats payload.

    Both ends hash the same canonical JSON (:func:`_json_str` — sorted
    keys, tight separators, round-trip stable for JSON scalars), so the
    worker's digest of what it sent equals the scheduler's digest of what
    it decoded; a HB_SEQ datagram then proves "stats unchanged" without
    re-shipping them."""
    return hashlib.blake2b(
        _json_str(stats).encode("utf-8"), digest_size=8
    ).hexdigest()


def encode_heartbeat_seq(worker_id: str, seq: int, digest: str) -> bytes:
    """HB_SEQ: liveness-only heartbeat — the stats payload is unchanged
    since the last full HEARTBEAT (``digest`` proves which one)."""
    return (_HDR.pack(ROOM_MAGIC, T_HEARTBEAT_SEQ) + _pack_str(worker_id)
            + _pack_u32(seq) + _pack_str(digest))


def encode_place(lobby_id: str, spec: dict) -> bytes:
    """PLACE: host this lobby from frame 0."""
    return (_HDR.pack(ROOM_MAGIC, T_PLACE) + _pack_str(lobby_id)
            + _pack_json(spec))


def encode_place_ok(lobby_id: str, frame: int) -> bytes:
    """PLACE_OK: the lobby is built and running."""
    return (_HDR.pack(ROOM_MAGIC, T_PLACE_OK) + _pack_str(lobby_id)
            + _pack_u32(frame))


def encode_drain(lobby_id: str, barrier_frame: int) -> bytes:
    """DRAIN: advance exactly to ``barrier_frame``, checkpoint, ship."""
    return (_HDR.pack(ROOM_MAGIC, T_DRAIN) + _pack_str(lobby_id)
            + _pack_u32(barrier_frame))


def encode_ckpt_chunk(lobby_id: str, frame: int, seq: int, total: int,
                      chunk: bytes) -> bytes:
    """CKPT: one chunk of a (lobby, frame) checkpoint."""
    return (_HDR.pack(ROOM_MAGIC, T_CKPT) + _pack_str(lobby_id)
            + _pack_u32(frame) + _pack_u16(seq) + _pack_u16(total) + chunk)


def encode_ckpt_ack(lobby_id: str, frame: int) -> bytes:
    """CKPT_ACK: the full (lobby, frame) checkpoint arrived."""
    return (_HDR.pack(ROOM_MAGIC, T_CKPT_ACK) + _pack_str(lobby_id)
            + _pack_u32(frame))


def encode_resume(lobby_id: str, frame: int, spec: dict) -> bytes:
    """RESUME: restore (lobby, frame) from the CKPT chunks that follow."""
    return (_HDR.pack(ROOM_MAGIC, T_RESUME) + _pack_str(lobby_id)
            + _pack_u32(frame) + _pack_json(spec))


def encode_resume_ok(lobby_id: str, frame: int) -> bytes:
    """RESUME_OK: restored at ``frame`` and running."""
    return (_HDR.pack(ROOM_MAGIC, T_RESUME_OK) + _pack_str(lobby_id)
            + _pack_u32(frame))


def encode_drop(lobby_id: str) -> bytes:
    """DROP: forget a lobby (post-migration source cleanup)."""
    return _HDR.pack(ROOM_MAGIC, T_DROP) + _pack_str(lobby_id)


def encode_submit(lobby_id: str, spec: dict) -> bytes:
    """SUBMIT: a client asks the scheduler to place a lobby."""
    return (_HDR.pack(ROOM_MAGIC, T_SUBMIT) + _pack_str(lobby_id)
            + _pack_json(spec))


def encode_submit_ok(lobby_id: str, worker_id: str) -> bytes:
    """SUBMIT_OK: admitted; ``worker_id`` hosts it."""
    return (_HDR.pack(ROOM_MAGIC, T_SUBMIT_OK) + _pack_str(lobby_id)
            + _pack_str(worker_id))


def encode_reject(lobby_id: str, reason: str) -> bytes:
    """REJECT: admission refused, with the wire-visible reason."""
    return (_HDR.pack(ROOM_MAGIC, T_REJECT) + _pack_str(lobby_id)
            + _pack_str(reason))


def encode_done(lobby_id: str, frame: int, checksum_hex: str) -> bytes:
    """DONE: the lobby reached its target frame; final checksum attached."""
    return (_HDR.pack(ROOM_MAGIC, T_DONE) + _pack_str(lobby_id)
            + _pack_u32(frame) + _pack_str(checksum_hex))


def decode(data: bytes) -> Optional[Msg]:
    """Decode one fleet datagram; None for non-fleet or malformed bytes
    (same drop-don't-crash posture as the room decoders — every input is
    untrusted)."""
    if len(data) < _HDR.size:
        return None
    magic, t = _HDR.unpack_from(data)
    if magic != ROOM_MAGIC:
        return None
    r = _Reader(data[_HDR.size:])
    if t == T_REGISTER:
        wid = r.s()
        cap = struct.unpack("<H", r.take(2))[0] if r.ok else 0
        if not r.ok or not wid:
            return None
        return Msg(t, a=wid, total=cap)
    if t == T_HEARTBEAT:
        wid = r.s()
        obj = _read_json(r)
        if not r.ok or not wid or not isinstance(obj, dict):
            return None
        return Msg(t, a=wid, obj=obj)
    if t == T_HEARTBEAT_SEQ:
        wid = r.s()
        seq = _u32(r)
        dig = r.s()
        if not r.ok or not wid or not dig:
            return None
        return Msg(t, a=wid, b=dig, seq=seq)
    if t in (T_PLACE, T_RESUME, T_SUBMIT):
        lid = r.s()
        frame = _u32(r) if t == T_RESUME else 0
        obj = _read_json(r)
        if not r.ok or not lid or not isinstance(obj, dict):
            return None
        return Msg(t, a=lid, frame=frame, obj=obj)
    if t in (T_PLACE_OK, T_RESUME_OK, T_CKPT_ACK, T_DRAIN):
        lid = r.s()
        frame = _u32(r)
        if not r.ok or not lid:
            return None
        return Msg(t, a=lid, frame=frame)
    if t == T_CKPT:
        lid = r.s()
        frame = _u32(r)
        seq = struct.unpack("<H", r.take(2))[0] if r.ok else 0
        total = struct.unpack("<H", r.take(2))[0] if r.ok else 0
        blob = r.rest()
        if not r.ok or not lid or total == 0 or seq >= total:
            return None
        return Msg(t, a=lid, frame=frame, seq=seq, total=total, blob=blob)
    if t == T_DROP:
        lid = r.s()
        if not r.ok or not lid:
            return None
        return Msg(t, a=lid)
    if t in (T_SUBMIT_OK, T_REJECT):
        lid = r.s()
        second = r.s()
        if not r.ok or not lid:
            return None
        return Msg(t, a=lid, b=second)
    if t == T_DONE:
        lid = r.s()
        frame = _u32(r)
        cks = r.s()
        if not r.ok or not lid:
            return None
        return Msg(t, a=lid, frame=frame, b=cks)
    return None


def chunk_checkpoint(lobby_id: str, frame: int, blob: bytes) -> List[bytes]:
    """Split a checkpoint into CKPT datagrams (>= 1 even when empty)."""
    total = max(1, (len(blob) + CKPT_CHUNK_BYTES - 1) // CKPT_CHUNK_BYTES)
    if total > 0xFFFF:
        raise ValueError(
            f"checkpoint of {len(blob)} bytes needs {total} chunks "
            "(u16 ceiling) — raise CKPT_CHUNK_BYTES or compress harder"
        )
    return [
        encode_ckpt_chunk(
            lobby_id, frame, i, total,
            blob[i * CKPT_CHUNK_BYTES:(i + 1) * CKPT_CHUNK_BYTES],
        )
        for i in range(total)
    ]


class ChunkAssembler:
    """Reassembles chunked checkpoints keyed by ``(lobby_id, frame)``.

    Chunks may arrive in any order (UDP); a later frame's first chunk for
    the same lobby drops the stale partial (only one checkpoint per lobby
    is ever in flight from one sender).  ``offer`` returns the complete
    blob exactly once, when the last missing chunk lands."""

    def __init__(self):
        self._parts = {}  # (lobby, frame) -> {seq: bytes}; totals implicit

    def offer(self, msg: Msg) -> Optional[bytes]:
        """Feed one CKPT message; returns the full blob when complete."""
        key = (msg.a, msg.frame)
        # supersede any older in-flight checkpoint for this lobby
        for stale in [k for k in self._parts
                      if k[0] == msg.a and k[1] < msg.frame]:
            del self._parts[stale]
        parts = self._parts.setdefault(key, {})
        parts[msg.seq] = msg.blob
        # completeness by explicit coverage, not count: a malformed sender
        # mixing totals for one key must never KeyError the join
        if any(i not in parts for i in range(msg.total)):
            return None
        del self._parts[key]
        return b"".join(parts[i] for i in range(msg.total))

    def pending(self) -> List[Tuple[str, int]]:
        """Keys of incomplete checkpoints (diagnostics)."""
        return sorted(self._parts)
