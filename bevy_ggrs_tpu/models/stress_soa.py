"""stress_soa — the benchmark workload with per-coordinate scalar columns.

Same simulation as :mod:`stress` (Transform+Velocity under gravity with
bounces), but each coordinate is its own ``[N]`` column (x/y/z/vx/vy/vz)
instead of two ``[N, 3]`` matrices.  On TPU the entity axis then lands in
the lane (minor) dimension and tiles (8,128) natively, where ``[N, 3]``
pads the minor dim 3 -> 128 (docs/tpu_notes.md §2).  bench.py measures both
layouts and reports the better one as the headline."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..app import App
from ..snapshot.world import active_mask, spawn_many

GRAVITY = np.float32(-9.8)
BOUND = np.float32(50.0)

_COLS = ("x", "y", "z", "vx", "vy", "vz")


def step(world, ctx):
    """Same physics as stress.step over per-coordinate scalar columns."""
    m = active_mask(world)
    dt = ctx.delta_seconds
    c = world.comps
    vy = c["vy"] + GRAVITY * dt
    new = {
        "vx": c["vx"], "vy": vy, "vz": c["vz"],
        "x": c["x"] + c["vx"] * dt,
        "y": c["y"] + vy * dt,
        "z": c["z"] + c["vz"] * dt,
    }
    for p, v in (("x", "vx"), ("y", "vy"), ("z", "vz")):
        over = jnp.abs(new[p]) > BOUND
        new[v] = jnp.where(over, -new[v], new[v])
        new[p] = jnp.clip(new[p], -BOUND, BOUND)
    return dataclasses.replace(
        world, comps={k: jnp.where(m, new[k], c[k]) for k in _COLS}
    )


def make_app(n_entities: int = 10_000, capacity: int | None = None,
             fps: int = 60, checksum: bool = True, seed: int = 0,
             canonical_depth: int | None = None) -> App:
    """Build the scalar-column benchmark App with n_entities pre-spawned.

    Pass ``canonical_depth`` for cross-host bit-determinism of the float
    physics: the fleet lobby catalog (fleet/lobby.py) needs every advance —
    whatever its chunking before/after a migration — to run through ONE
    compiled program (docs/determinism.md "One program to advance them
    all")."""
    capacity = capacity or n_entities
    app = App(num_players=2, capacity=capacity, fps=fps,
              input_shape=(), input_dtype=np.uint8, seed=seed,
              canonical_depth=canonical_depth)
    for name in _COLS:
        app.rollback_component(name, (), jnp.float32, checksum=checksum)
    app.set_step(step)

    def setup(world):
        rng = np.random.default_rng(seed)
        cols = {}
        for name in ("x", "y", "z"):
            cols[name] = jnp.asarray(
                rng.uniform(-40, 40, n_entities).astype(np.float32)
            )
        for name in ("vx", "vy", "vz"):
            cols[name] = jnp.asarray(
                rng.uniform(-5, 5, n_entities).astype(np.float32)
            )
        return spawn_many(app.reg, world, cols, count=n_entities)

    app.set_setup(setup)
    return app
