"""fixed_point — integer-math box_game for cross-backend determinism.

The reference warns that f32 math differs across platforms
(/root/reference/docs/debugging-desyncs.md:55); mixed-platform lobbies need
integer simulation math.  This model re-expresses the box_game ice physics
in Q16.16 fixed point (int32 columns, shifts and integer multiplies only),
so CPU and TPU produce bit-identical states and therefore exactly equal
checksums — the "SyncTest checksum parity" oracle in BASELINE.md.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..app import App
from ..ops.resim import StepCtx
from ..snapshot.world import WorldState, active_mask, spawn

FP = 16  # fractional bits
ONE = 1 << FP

ACCEL = ONE // 200  # per-frame acceleration in Q16.16
# friction 255/256 per frame, exact in integers
ARENA_HALF = 4 * ONE


def _q_mul(a, b):
    """Q16.16 multiply without int64: split b into hi/lo 16-bit halves."""
    bh = b >> FP
    bl = b & (ONE - 1)
    return a * bh + ((a * bl) >> FP)


def step(world: WorldState, ctx: StepCtx) -> WorldState:
    """Q16.16 integer box_game step (bit-identical across backends)."""
    handle = world.comps["handle"]
    mask = active_mask(world) & world.has["handle"]
    inp = ctx.inputs.reshape(-1)[jnp.clip(handle, 0, ctx.inputs.shape[0] - 1)]
    inp = jnp.where(mask, inp, 0).astype(jnp.int32)

    def bit(b):
        return (inp >> b) & 1

    acc_x = (bit(3) - bit(2)) * ACCEL
    acc_z = (bit(1) - bit(0)) * ACCEL

    vel = world.comps["vel"]
    vel = vel + jnp.stack([acc_x, acc_z], axis=-1)
    vel = (vel * 255) >> 8  # friction, arithmetic shift (exact, wrapping-safe)

    pos = world.comps["pos"] + vel
    pos = jnp.clip(pos, -ARENA_HALF, ARENA_HALF)

    m = mask[:, None]
    return dataclasses.replace(
        world,
        comps={
            **world.comps,
            "vel": jnp.where(m, vel, world.comps["vel"]),
            "pos": jnp.where(m, pos, world.comps["pos"]),
        },
    )


def make_app(num_players: int = 2, capacity: int = 8, fps: int = 60) -> App:
    """Build the fixed-point App (int32 pos/vel in Q16.16)."""
    app = App(num_players=num_players, capacity=capacity, fps=fps,
              input_shape=(), input_dtype=np.uint8)
    app.rollback_component("pos", (2,), jnp.int32, checksum=True)
    app.rollback_component("vel", (2,), jnp.int32, checksum=True)
    app.rollback_component("handle", (), jnp.int32, checksum=True)
    app.set_step(step)

    def setup(world):
        for h in range(num_players):
            world, _ = spawn(
                app.reg, world,
                {"pos": np.array([(h * 2 - 1) * 2 * ONE, 0], np.int32),
                 "vel": np.zeros(2, np.int32),
                 "handle": h},
            )
        return world

    app.set_setup(setup)
    return app


def to_float(q):
    """Q16.16 -> float for display."""
    return np.asarray(q, np.float64) / ONE
