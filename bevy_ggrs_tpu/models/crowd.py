"""crowd — large-scale flocking model (the multi-chip showcase).

Unlike box_game/particles (pure per-entity physics), each crowd member
steers toward its team's centroid and away from the global center of mass —
cross-entity *reductions* that exercise the MXU (the team reduction is a
one-hot ``[N, T] @ [N, 2]`` matmul) and, under entity-axis sharding, XLA
collectives (the segment sums become psums on the mesh).  Inputs steer each
player's team (one team per player handle).

All reductions are sums of f32 — deterministic within a backend for a fixed
sharding, and the order is fixed by the mesh, so SyncTest stays clean; for
cross-backend lobbies use the fixed_point model instead (docs/determinism.md).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..app import App
from ..ops.resim import StepCtx
from ..snapshot.world import WorldState, active_mask, spawn_many

COHESION = np.float32(0.4)
REPULSION = np.float32(0.15)
STEER = np.float32(2.0)
DRAG = np.float32(0.98)
BOUND = np.float32(30.0)


def make_step(app: App, num_teams: int):
    """Build the flocking step (team centroids via one-hot matmul)."""
    def step(world: WorldState, ctx: StepCtx) -> WorldState:
        m = active_mask(world) & world.has["team"]
        mf = m.astype(jnp.float32)
        pos, vel = world.comps["pos"], world.comps["vel"]
        team = jnp.clip(world.comps["team"], 0, num_teams - 1)

        # team centroids via one-hot matmul (MXU work; psum under sharding)
        onehot = jax.nn.one_hot(team, num_teams, dtype=jnp.float32) * mf[:, None]
        team_sum = onehot.T @ pos  # [T, 2]
        team_cnt = jnp.maximum(onehot.sum(axis=0), 1.0)  # [T]
        centroids = team_sum / team_cnt[:, None]

        # global center of mass (repulsion keeps teams apart)
        total = jnp.maximum(mf.sum(), 1.0)
        com = (pos * mf[:, None]).sum(axis=0) / total

        # player steering: input bitmask accelerates the whole team
        inp = ctx.inputs.reshape(-1)[jnp.clip(team, 0, ctx.inputs.shape[0] - 1)]
        inp = jnp.where(m, inp, 0).astype(jnp.int32)

        def bit(b):
            return ((inp >> b) & 1).astype(jnp.float32)

        steer = jnp.stack([bit(3) - bit(2), bit(1) - bit(0)], axis=-1) * STEER

        to_centroid = centroids[team] - pos
        from_com = pos - com[None, :]
        acc = COHESION * to_centroid + REPULSION * from_com + steer
        vel = (vel + acc * ctx.delta_seconds) * DRAG
        pos = jnp.clip(pos + vel * ctx.delta_seconds, -BOUND, BOUND)

        m2 = m[:, None]
        return dataclasses.replace(
            world,
            comps={
                **world.comps,
                "pos": jnp.where(m2, pos, world.comps["pos"]),
                "vel": jnp.where(m2, vel, world.comps["vel"]),
            },
        )

    return step


def make_app(n_per_team: int = 512, num_teams: int = 2, capacity: int | None = None,
             fps: int = 60, seed: int = 0) -> App:
    """Build the crowd App: n_per_team boids per player-controlled team."""
    n = n_per_team * num_teams
    capacity = capacity or n
    app = App(num_players=num_teams, capacity=capacity, fps=fps,
              input_shape=(), input_dtype=np.uint8, seed=seed)
    app.rollback_component("pos", (2,), jnp.float32, checksum=True)
    app.rollback_component("vel", (2,), jnp.float32, checksum=True)
    app.rollback_component("team", (), jnp.int32, checksum=True)
    app.set_step(make_step(app, num_teams))

    def setup(world):
        rng = np.random.default_rng(seed)
        pos = rng.uniform(-20, 20, (n, 2)).astype(np.float32)
        team = np.repeat(np.arange(num_teams, dtype=np.int32), n_per_team)
        return spawn_many(
            app.reg, world,
            {"pos": jnp.asarray(pos), "vel": jnp.zeros((n, 2), jnp.float32),
             "team": jnp.asarray(team)},
            count=n,
        )

    app.set_setup(setup)
    return app
