from . import box_game, particles, stress, fixed_point

__all__ = ["box_game", "particles", "stress", "fixed_point"]
