from . import box_game, particles, stress, stress_soa, fixed_point

__all__ = ["box_game", "particles", "stress", "stress_soa", "fixed_point"]
