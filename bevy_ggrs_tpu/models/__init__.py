from . import box_game

__all__ = ["box_game"]
