from . import box_game, crowd, particles, pong, stress, stress_soa, fixed_point

__all__ = ["box_game", "crowd", "particles", "pong", "stress", "stress_soa", "fixed_point"]
