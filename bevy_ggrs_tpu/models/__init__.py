from . import box_game, particles

__all__ = ["box_game", "particles"]
