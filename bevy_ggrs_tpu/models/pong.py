"""pong — a complete two-player game on the framework.

Demonstrates the full API surface working together the way a real game uses
it: paddle entities driven by inputs, a ball that despawns on goals and
respawns after a serve delay (deferred despawn + spawn under jit), a score
resource, and a win condition — all rollback-safe and checksummed.  Input
bits: UP=1, DOWN=2.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..app import App
from ..ops.resim import StepCtx
from ..snapshot.world import WorldState, active_mask, despawn_where, spawn, spawn_many

UP, DOWN = 1, 2

COURT_W = np.float32(8.0)  # half-extent x
COURT_H = np.float32(4.5)  # half-extent y
PADDLE_X = np.float32(7.5)
PADDLE_HALF = np.float32(1.0)
PADDLE_SPEED = np.float32(6.0)
BALL_SPEED = np.float32(6.0)
SERVE_DELAY = 45  # frames between goal and re-serve
WIN_SCORE = 11

# entity kinds
K_PADDLE = 0
K_BALL = 1


def step(world: WorldState, ctx: StepCtx) -> WorldState:
    """Paddles + ball + goals + serve cycle (see module docstring)."""
    m = active_mask(world)
    kind = world.comps["kind"]
    owner = world.comps["owner"]
    pos = world.comps["pos"]
    vel = world.comps["vel"]

    is_paddle = m & (kind == K_PADDLE)
    is_ball = m & (kind == K_BALL)

    # ---- paddles: input-driven vertical movement
    inp = ctx.inputs.reshape(-1)[jnp.clip(owner, 0, ctx.inputs.shape[0] - 1)]
    inp = jnp.where(is_paddle, inp, 0).astype(jnp.int32)
    dy = (((inp >> 0) & 1) - ((inp >> 1) & 1)).astype(jnp.float32) * PADDLE_SPEED
    pad_y = jnp.clip(
        pos[:, 1] + dy * ctx.delta_seconds,
        -COURT_H + PADDLE_HALF, COURT_H - PADDLE_HALF,
    )
    pos = pos.at[:, 1].set(jnp.where(is_paddle, pad_y, pos[:, 1]))

    # ---- ball: integrate, bounce off walls and paddles
    bpos = pos + vel * ctx.delta_seconds
    bvel = vel
    # wall bounce (top/bottom)
    hit_wall = jnp.abs(bpos[:, 1]) > COURT_H
    bvel = bvel.at[:, 1].set(jnp.where(hit_wall, -bvel[:, 1], bvel[:, 1]))
    bpos = bpos.at[:, 1].set(jnp.clip(bpos[:, 1], -COURT_H, COURT_H))
    # paddle bounce: compare ball y against the owning side's paddle y
    paddle_y = jnp.sum(
        jnp.where(is_paddle & (owner == 0), pos[:, 1], 0.0)
    ), jnp.sum(jnp.where(is_paddle & (owner == 1), pos[:, 1], 0.0))
    p0y, p1y = paddle_y
    near_p0 = (bpos[:, 0] < -PADDLE_X) & (jnp.abs(bpos[:, 1] - p0y) <= PADDLE_HALF)
    near_p1 = (bpos[:, 0] > PADDLE_X) & (jnp.abs(bpos[:, 1] - p1y) <= PADDLE_HALF)
    bounce = (near_p0 & (bvel[:, 0] < 0)) | (near_p1 & (bvel[:, 0] > 0))
    bvel = bvel.at[:, 0].set(jnp.where(bounce, -bvel[:, 0] * 1.05, bvel[:, 0]))
    bpos = bpos.at[:, 0].set(
        jnp.where(bounce, jnp.clip(bpos[:, 0], -PADDLE_X, PADDLE_X), bpos[:, 0])
    )

    pos = jnp.where(is_ball[:, None], bpos, pos)
    vel = jnp.where(is_ball[:, None], bvel, vel)

    # ---- goals: ball fully past a goal line (and not bounced)
    goal_p1 = is_ball & (pos[:, 0] <= -COURT_W)  # player 1 scores
    goal_p0 = is_ball & (pos[:, 0] >= COURT_W)  # player 0 scores
    scored_any = jnp.any(goal_p0) | jnp.any(goal_p1)
    score = world.res["score"]
    score = score.at[0].add(jnp.sum(goal_p0).astype(jnp.int32))
    score = score.at[1].add(jnp.sum(goal_p1).astype(jnp.int32))
    world = dataclasses.replace(
        world,
        comps={**world.comps, "pos": pos, "vel": vel},
        res={**world.res, "score": score},
    )
    world = despawn_where(_REG[0], world, goal_p0 | goal_p1, ctx.frame)

    # ---- serve: respawn the ball after the delay (deterministic direction)
    serve_at = world.res["serve_at"]
    serve_at = jnp.where(
        scored_any, ctx.frame + SERVE_DELAY, serve_at
    ).astype(jnp.int32)
    game_over = (score[0] >= WIN_SCORE) | (score[1] >= WIN_SCORE)
    do_serve = (serve_at == ctx.frame) & ~game_over
    direction = jnp.where((score[0] + score[1]) % 2 == 0, 1.0, -1.0)
    tilt = jnp.where(ctx.frame % 3 == 0, 0.35, -0.5).astype(jnp.float32)
    new_ball = {
        "pos": jnp.zeros((1, 2), jnp.float32),
        "vel": jnp.stack(
            [direction * BALL_SPEED, tilt * BALL_SPEED]
        ).astype(jnp.float32)[None],
        "kind": jnp.full((1,), K_BALL, jnp.int32),
        "owner": jnp.full((1,), -1, jnp.int32),
    }
    world = spawn_many(
        _REG[0], world, new_ball, count=jnp.where(do_serve, 1, 0)
    )
    return dataclasses.replace(
        world, res={**world.res, "serve_at": serve_at}
    )


_REG = [None]  # registry handle for spawn_many inside the jitted step


def make_app(fps: int = 60, capacity: int = 16, canonical_depth=None) -> App:
    """Build the pong App (paddle entities, score/serve resources).

    ``canonical_depth``: see docs/determinism.md (float bit-determinism)."""
    app = App(num_players=2, capacity=capacity, fps=fps,
              input_shape=(), input_dtype=np.uint8,
              canonical_depth=canonical_depth)
    app.rollback_component("pos", (2,), jnp.float32, checksum=True)
    app.rollback_component("vel", (2,), jnp.float32, checksum=True)
    app.rollback_component("kind", (), jnp.int32, checksum=True)
    app.rollback_component("owner", (), jnp.int32, checksum=True)
    app.rollback_resource("score", np.zeros(2, np.int32), checksum=True)
    app.rollback_resource("serve_at", np.int32(1), checksum=True)
    _REG[0] = app.reg
    app.set_step(step)

    def setup(world):
        for h in range(2):
            world, _ = spawn(
                app.reg, world,
                {"pos": np.array([(-1 if h == 0 else 1) * PADDLE_X, 0.0],
                                 np.float32),
                 "vel": np.zeros(2, np.float32),
                 "kind": K_PADDLE, "owner": h},
            )
        return world

    app.set_setup(setup)
    return app


def winner(world) -> int:
    """-1 while playing, else the winning handle."""
    s = np.asarray(world.res["score"])
    if s[0] >= WIN_SCORE:
        return 0
    if s[1] >= WIN_SCORE:
        return 1
    return -1
