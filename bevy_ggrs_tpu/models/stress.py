"""stress — the benchmark workload: N pre-spawned entities with
Transform+Velocity, integrated under gravity with arena bounces, 8-frame
rollback resimulation (BASELINE.md config 3: "10k entities,
Transform+Velocity, 8-frame rollback")."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..app import App
from ..snapshot.world import active_mask, spawn_many

GRAVITY = np.float32(-9.8)
BOUND = np.float32(50.0)


def step(world, ctx):
    """Gravity integration with elastic arena bounces ([N,3] columns)."""
    m = active_mask(world)[:, None]
    vel = world.comps["vel"] + jnp.array([0.0, GRAVITY, 0.0]) * ctx.delta_seconds
    pos = world.comps["pos"] + vel * ctx.delta_seconds
    # elastic bounce at the arena bounds
    over = jnp.abs(pos) > BOUND
    vel = jnp.where(over, -vel, vel)
    pos = jnp.clip(pos, -BOUND, BOUND)
    return dataclasses.replace(
        world,
        comps={
            "pos": jnp.where(m, pos, world.comps["pos"]),
            "vel": jnp.where(m, vel, world.comps["vel"]),
        },
    )


def make_app(n_entities: int = 10_000, capacity: int | None = None, fps: int = 60,
             checksum: bool = True, seed: int = 0, num_players: int = 2) -> App:
    """Build the benchmark workload App with n_entities pre-spawned."""
    capacity = capacity or n_entities
    app = App(num_players=num_players, capacity=capacity, fps=fps,
              input_shape=(), input_dtype=np.uint8, seed=seed)
    app.rollback_component("pos", (3,), jnp.float32, checksum=checksum)
    app.rollback_component("vel", (3,), jnp.float32, checksum=checksum)
    app.set_step(step)

    def setup(world):
        rng = np.random.default_rng(seed)
        pos = rng.uniform(-40, 40, (n_entities, 3)).astype(np.float32)
        vel = rng.uniform(-5, 5, (n_entities, 3)).astype(np.float32)
        return spawn_many(
            app.reg, world, {"pos": jnp.asarray(pos), "vel": jnp.asarray(vel)},
            count=n_entities,
        )

    app.set_setup(setup)
    return app
