"""particles — the stress-test / benchmark workload.

Behavioral port of the reference's particles stress test
(/root/reference/examples/stress_tests/particles.rs): every frame spawn
``rate`` particles with seeded-random velocity and ttl, integrate gravity,
decrement ttl, despawn on expiry; the RNG state is itself rollback state
(particles.rs:125-128,243 keeps a Xoshiro256PlusPlus as a rollback resource)
so resimulated frames reproduce identical spawns; Transform participates in
the checksum via its raw f32 bit pattern (particles.rs:207-222).

TPU-native shape: a fixed-capacity pool, ``spawn_many`` scatter per frame, a
counter-based PRNG (one uint32 counter resource -> ``jax.random`` key per
frame — the rollback-able equivalent of the sequential Xoshiro), and all
physics as masked SoA ops.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..app import App
from ..ops.resim import StepCtx
from ..snapshot.world import WorldState, active_mask, despawn_where, spawn_many

GRAVITY = np.float32(-9.8)
DEFAULT_TTL = 120  # frames (2 s at 60 fps, particles.rs ttl)


def make_step(app: App, rate: int, ttl: int = DEFAULT_TTL):
    """Build the particles step: ttl decay, gravity, seeded spawn bursts."""
    reg = app.reg

    def step(world: WorldState, ctx: StepCtx) -> WorldState:
        m = active_mask(world) & world.has["ttl"]
        # ttl decrement + expiry despawn
        new_ttl = jnp.where(m, world.comps["ttl"] - 1, world.comps["ttl"])
        world = dataclasses.replace(world, comps={**world.comps, "ttl": new_ttl})
        world = despawn_where(reg, world, m & (new_ttl <= 0), ctx.frame)

        # integrate
        m3 = (active_mask(world) & world.has["vel"])[:, None]
        vel = world.comps["vel"] + jnp.array([0.0, GRAVITY, 0.0]) * ctx.delta_seconds
        pos = world.comps["pos"] + vel * ctx.delta_seconds
        world = dataclasses.replace(
            world,
            comps={
                **world.comps,
                "vel": jnp.where(m3, vel, world.comps["vel"]),
                "pos": jnp.where(m3, pos, world.comps["pos"]),
            },
        )

        # seeded spawn burst — RNG counter is a rollback resource, so a resim
        # of this frame reproduces the exact same particles
        counter = world.res["rng_counter"]
        key = jax.random.fold_in(jax.random.PRNGKey(app.seed), counter)
        kv, kp = jax.random.split(key)
        new_vel = jax.random.uniform(
            kv, (rate, 3), jnp.float32, minval=-2.0, maxval=2.0
        )
        new_pos = jnp.zeros((rate, 3), jnp.float32).at[:, 1].set(
            jax.random.uniform(kp, (rate,), jnp.float32)
        )
        world = spawn_many(
            reg,
            world,
            {
                "pos": new_pos,
                "vel": new_vel,
                "ttl": jnp.full((rate,), ttl, jnp.int32),
            },
            count=rate,
        )
        return dataclasses.replace(
            world, res={**world.res, "rng_counter": counter + 1}
        )

    return step


def make_app(
    rate: int = 100,
    ttl: int = DEFAULT_TTL,
    capacity: int | None = None,
    num_players: int = 2,
    fps: int = 60,
    checksum: bool = True,
    seed: int = 0,
    quantize: bool = False,
) -> App:
    """Build the particles stress App (capacity sized for rate x ttl).

    ``quantize`` stores the float columns' ring snapshots in bf16 — the
    registration-strategy A/B knob of the reference's ``--reflect`` flag
    (/root/reference/examples/stress_tests/particles.rs:169-201), exercising
    the only non-identity Strategy under checksums/desync detection."""
    if capacity is None:
        capacity = rate * (ttl + 8) + 64  # steady state + rollback headroom
    app = App(
        num_players=num_players,
        capacity=capacity,
        fps=fps,
        input_shape=(),
        input_dtype=np.uint8,
        seed=seed,
    )
    from ..snapshot.strategy import CopyStrategy, QuantizeStrategy

    strat = QuantizeStrategy(jnp.bfloat16) if quantize else CopyStrategy
    app.rollback_component("pos", (3,), jnp.float32, checksum=checksum,
                           strategy=strat)
    app.rollback_component("vel", (3,), jnp.float32, checksum=checksum,
                           strategy=strat)
    app.rollback_component("ttl", (), jnp.int32, checksum=checksum)
    app.rollback_resource("rng_counter", jnp.uint32(0), checksum=checksum)
    app.set_step(make_step(app, rate, ttl))
    return app
