"""box_game — the canonical 2-4 player example model.

Behavioral port of the reference's shared box_game logic
(/root/reference/examples/box_game/box_game.rs): each player is a cube on an
ice rink driven by a 4-bit direction bitmask input (``BoxInput(u8)``,
box_game.rs:34-38); acceleration from input, friction decay, positions
clamped to the rink.  Re-expressed as a pure vectorized step over SoA columns
— per-player independence is what made the reference's unsorted query
iteration safe (box_game.rs:162-169); here it is a plain masked array op.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..app import App
from ..ops.resim import StepCtx
from ..snapshot.world import WorldState, active_mask, spawn

INPUT_UP = 1 << 0
INPUT_DOWN = 1 << 1
INPUT_LEFT = 1 << 2
INPUT_RIGHT = 1 << 3

# numpy scalars (not jnp): module-level device arrays captured in jit are a
# measured per-call slow path on the TPU tunnel; numpy embeds as literals
MOVEMENT_SPEED = np.float32(0.005)
MAX_SPEED = np.float32(0.05)
FRICTION = np.float32(0.9975)
ARENA_HALF = np.float32(4.0)


def step(world: WorldState, ctx: StepCtx) -> WorldState:
    """Ice-rink cube physics: input acceleration, friction, clamped arena."""
    handle = world.comps["handle"].astype(jnp.int32)
    mask = active_mask(world) & world.has["handle"]
    # gather this entity's input byte by player handle
    inp = ctx.inputs.reshape(-1)[jnp.clip(handle, 0, ctx.inputs.shape[0] - 1)]
    inp = jnp.where(mask, inp, 0).astype(jnp.uint8)

    def bit(b):
        return ((inp >> b) & 1).astype(jnp.float32)

    acc_x = (bit(3) - bit(2)) * MOVEMENT_SPEED  # right - left
    acc_z = (bit(1) - bit(0)) * MOVEMENT_SPEED  # down - up

    vel = world.comps["vel"]
    vel = vel + jnp.stack([acc_x, acc_z], axis=-1)
    vel = vel * FRICTION
    speed = jnp.sqrt(jnp.sum(vel * vel, axis=-1, keepdims=True))
    scale = jnp.where(speed > MAX_SPEED, MAX_SPEED / jnp.maximum(speed, 1e-9), 1.0)
    vel = vel * scale

    pos = world.comps["pos"] + vel
    pos = jnp.clip(pos, -ARENA_HALF, ARENA_HALF)

    m = mask[:, None]
    import dataclasses

    return dataclasses.replace(
        world,
        comps={
            **world.comps,
            "vel": jnp.where(m, vel, world.comps["vel"]),
            "pos": jnp.where(m, pos, world.comps["pos"]),
        },
    )


def setup(app: App):
    """Spawn one cube per player at spread-out rink positions
    (box_game.rs spawn pattern: players on a circle)."""

    def fn(world: WorldState) -> WorldState:
        n = app.num_players
        for h in range(n):
            angle = 2.0 * np.pi * h / n
            pos = jnp.array(
                [np.cos(angle) * 2.0, np.sin(angle) * 2.0], jnp.float32
            )
            world, _ = spawn(
                app.reg,
                world,
                {"pos": pos, "vel": jnp.zeros(2, jnp.float32), "handle": h},
            )
        return world

    return fn


def make_app(num_players: int = 2, capacity: int = 8, fps: int = 60,
             canonical_depth=None) -> App:
    """Build the box_game App (pos/vel/handle columns, checksummed).

    Pass ``canonical_depth`` for cross-peer bit-determinism hardening of the
    float physics (docs/determinism.md "One program to advance them all")."""
    app = App(
        num_players=num_players,
        capacity=capacity,
        fps=fps,
        input_shape=(),
        input_dtype=np.uint8,
        canonical_depth=canonical_depth,
    )
    app.rollback_component("pos", (2,), jnp.float32, checksum=True)
    app.rollback_component("vel", (2,), jnp.float32, checksum=True)
    app.rollback_component("handle", (), jnp.int32, checksum=True)
    app.set_step(step)
    app.set_setup(setup(app))
    return app


def keys_to_input(up=False, down=False, left=False, right=False) -> np.uint8:
    """Keyboard -> BoxInput bitmask (box_game.rs:60-87 read_local_inputs)."""
    v = 0
    if up:
        v |= INPUT_UP
    if down:
        v |= INPUT_DOWN
    if left:
        v |= INPUT_LEFT
    if right:
        v |= INPUT_RIGHT
    return np.uint8(v)
