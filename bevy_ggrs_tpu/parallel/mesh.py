"""Device-mesh sharding of the rollback world — scale past one chip.

The reference is a single-process library; its scaling axes are entity count
and rollback depth.  Here the entity (capacity) axis shards across a
``jax.sharding.Mesh`` "data" axis, and speculative input branches shard
across a "spec" axis — SPMD via sharding annotations, letting XLA insert the
collectives (the scaling-book recipe: pick a mesh, annotate, let XLA place
psum/all-gather on ICI).

Correctness notes:
- the checksum reduces over the entity axis with *wrapping uint32 addition*
  (snapshot/checksum.py:12-19) — associative/commutative integer arithmetic,
  exact under any sharding (a plain psum), so sharded and single-device runs
  produce bit-identical checksums as long as the state bits match;
- ``spawn``/``spawn_many`` use cumsum/argmax over the sharded axis, which XLA
  lowers to scan+collectives — deterministic regardless of layout.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..app import App
from ..ops.resim import resim
from ..snapshot.world import WorldState

DATA_AXIS = "data"
SPEC_AXIS = "spec"
LOBBY_AXIS = "lobby"


def make_lobby_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D device mesh over the ``"lobby"`` axis — the many-worlds scale-out
    shape (ops/batch.ShardedWaveExecutor): each device owns a contiguous
    block of lobby lanes and runs the SAME bucketed wave program on them,
    so a wave of M lobbies costs O(1) dispatches per device.

    Orthogonal to :func:`make_mesh`: that mesh shards ONE world over its
    entity axis; this one shards MANY whole worlds over the lobby axis
    (no collectives at all — lobbies never communicate)."""
    devices = devices if devices is not None else jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices < 1:
        raise ValueError(f"lobby mesh needs >= 1 device, got {n_devices}")
    return Mesh(np.array(devices[:n_devices]), (LOBBY_AXIS,))


def lobby_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """NamedSharding splitting the leading (lobby) axis over the mesh."""
    return NamedSharding(mesh, P(LOBBY_AXIS, *([None] * (ndim - 1))))


def shard_lobby_worlds(mesh: Mesh, worlds):
    """Place a stacked ``[M, ...]`` many-worlds pytree onto the lobby mesh
    (every leaf's leading axis split over ``"lobby"``; M must divide by the
    device count — the BatchedRunner pads its resident world to ensure it)."""
    return jax.device_put(
        worlds, jax.tree.map(lambda a: lobby_sharding(mesh, a.ndim), worlds)
    )


def make_mesh(
    n_data: Optional[int] = None, n_spec: int = 1, devices=None
) -> Mesh:
    """Build a (data x spec) device mesh from the available devices."""
    devices = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devices) // n_spec
    use = np.array(devices[: n_data * n_spec]).reshape(n_data, n_spec)
    return Mesh(use, (DATA_AXIS, SPEC_AXIS))


def world_sharding(reg, mesh: Mesh, world: WorldState):
    """NamedSharding pytree: capacity-axis leaves shard over "data",
    scalars/resources replicate."""
    cap = reg.capacity

    def leaf_sharding(x):
        if x.ndim >= 1 and x.shape[0] == cap:
            return NamedSharding(mesh, P(DATA_AXIS, *([None] * (x.ndim - 1))))
        return NamedSharding(mesh, P())

    return jax.tree.map(leaf_sharding, world)


def shard_world(app: App, mesh: Mesh, world: WorldState) -> WorldState:
    """Place a world onto the mesh with entity-axis sharding."""
    return jax.device_put(world, world_sharding(app.reg, mesh, world))


def make_sharded_resim_fn(app: App, mesh: Mesh):
    """jit resim with the world sharded over the mesh "data" axis.

    Shapes: inputs_seq [k, P, ...]; returns (final, stacked, checksums) with
    the same entity-axis sharding on states."""
    fps, seed, reg, step = app.fps, app.seed, app.reg, app.step
    retention = app.retention

    @jax.jit
    def fn(world, inputs_seq, status_seq, start_frame):
        return resim(
            reg, step, world, inputs_seq, status_seq, start_frame, retention,
            fps, seed
        )

    def wrapped(world, inputs_seq, status_seq, start_frame, _unused=None):
        world = shard_world(app, mesh, world)
        return fn(world, inputs_seq, status_seq, start_frame)

    return wrapped


def make_sharded_speculate_fn(app: App, mesh: Mesh):
    """Speculative fan-out with branches over "spec" x entities over "data".

    ``inputs_branches``: [M, k, P, ...] sharded over the "spec" axis; the
    broadcast world shards over "data".  One jit call evaluates all branches
    across the whole mesh."""
    fps, seed, reg, step = app.fps, app.seed, app.reg, app.step
    retention = app.retention

    @jax.jit
    def fn(world, inputs_branches, status_branches, start_frame):
        return jax.vmap(
            lambda inp, stat: resim(
                reg, step, world, inp, stat, start_frame, retention, fps, seed
            )
        )(inputs_branches, status_branches)

    def wrapped(world, inputs_branches, status_branches, start_frame, _unused=None):
        world = shard_world(app, mesh, world)
        spec_sharding = NamedSharding(
            mesh, P(SPEC_AXIS, *([None] * (inputs_branches.ndim - 1)))
        )
        inputs_branches = jax.device_put(inputs_branches, spec_sharding)
        status_branches = jax.device_put(
            status_branches,
            NamedSharding(mesh, P(SPEC_AXIS, *([None] * (status_branches.ndim - 1)))),
        )
        return fn(world, inputs_branches, status_branches, start_frame)

    return wrapped


def make_sharded_canonical_fn(app: App, mesh: Mesh):
    """The canonical [branches, depth] program sharded over the mesh:
    entities over "data", branch lanes over "spec" — the full TPU-first
    shape (bit-determinism + speculation + multi-chip in one dispatch).

    Signature matches ``app.branched_fn``:
    fn(world, inputs[B, K, P, ...], status[B, K, P], start_frame, n_real[B]).
    """
    fn = app.branched_fn  # jitted; sharding comes from input placement

    def wrapped(world, inputs_b, status_b, start_frame, n_real):
        world = shard_world(app, mesh, world)
        spec = lambda nd: NamedSharding(mesh, P(SPEC_AXIS, *([None] * (nd - 1))))
        inputs_b = jax.device_put(jax.numpy.asarray(inputs_b), spec(np.ndim(inputs_b)))
        status_b = jax.device_put(jax.numpy.asarray(status_b), spec(np.ndim(status_b)))
        n_real = jax.device_put(jax.numpy.asarray(n_real), spec(1))
        return fn(world, inputs_b, status_b, start_frame, n_real)

    return wrapped
