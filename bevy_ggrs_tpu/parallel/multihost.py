"""Multi-host scale-out: the distributed communication backend.

Two distinct communication planes exist in this framework (SURVEY §5.8):

1. **Peer input exchange** — tiny, latency-sensitive, host-side UDP/DCN,
   handled by the session layer (python or native C++).  This never touches
   the accelerator fabric; it is the analog of the reference's non-blocking
   UDP core and scales with the number of *players*, not devices.

2. **Simulation sharding** — when ONE peer's world is too big for one chip
   (massive crowd sims, server-side lockstep worlds), the entity axis shards
   over a multi-host ``jax.sharding.Mesh``; XLA places the collectives
   (the checksum reduce, spawn cumsum/argmax) on ICI within a slice and DCN
   across hosts.  This module wires that up.

The mesh construction puts the entity ("data") axis on the FASTEST fabric:
devices within a host/slice are contiguous along "data" so per-frame
collectives ride ICI; the branch ("spec") axis — which only communicates at
branch-select time — spans hosts.  With a single process this degrades to
:func:`bevy_ggrs_tpu.parallel.make_mesh`.

Typical SPMD deployment (one process per host, all running the same driver):

    from bevy_ggrs_tpu.parallel import multihost
    multihost.initialize(coordinator_address="host0:9999",
                         num_processes=4, process_id=RANK)
    mesh = multihost.make_multihost_mesh(n_spec=2)
    resim = make_sharded_resim_fn(app, mesh)

All hosts execute the same session-driven request stream (rollback netcode
is already a replicated-state model — every peer simulates everything), so
the only cross-host coordination needed beyond XLA collectives is identical
inputs, which the session layer already guarantees.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from .mesh import DATA_AXIS, SPEC_AXIS, Mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """``jax.distributed.initialize`` passthrough (no-op if single-process
    or already initialized)."""
    if num_processes is None or num_processes <= 1:
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError:
        pass  # already initialized


def make_multihost_mesh(n_spec: int = 1) -> Mesh:
    """Global mesh over every device of every process.

    Layout: devices are ordered process-major by ``jax.devices()``; we place
    "spec" across the *process* (DCN) dimension first so the "data" axis —
    which carries the per-frame collectives — stays within-host (ICI)."""
    devs = np.array(jax.devices())
    n = devs.size
    if n % n_spec:
        raise ValueError(f"{n} devices not divisible by n_spec={n_spec}")
    grid = devs.reshape(n_spec, n // n_spec).T  # [data, spec]
    return Mesh(grid, (DATA_AXIS, SPEC_AXIS))


def process_count() -> int:
    """jax.process_count passthrough."""
    return jax.process_count()


def is_primary() -> bool:
    """True on process 0 (the coordinating host)."""
    return jax.process_index() == 0
