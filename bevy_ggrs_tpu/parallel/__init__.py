from . import multihost
from .mesh import (
    DATA_AXIS,
    LOBBY_AXIS,
    SPEC_AXIS,
    lobby_sharding,
    make_lobby_mesh,
    make_mesh,
    shard_lobby_worlds,
    world_sharding,
    shard_world,
    make_sharded_resim_fn,
    make_sharded_speculate_fn,
    make_sharded_canonical_fn,
)

__all__ = [
    "multihost",
    "DATA_AXIS",
    "LOBBY_AXIS",
    "SPEC_AXIS",
    "lobby_sharding",
    "make_lobby_mesh",
    "make_mesh",
    "shard_lobby_worlds",
    "world_sharding",
    "shard_world",
    "make_sharded_resim_fn",
    "make_sharded_speculate_fn",
    "make_sharded_canonical_fn",
]
