from . import multihost
from .mesh import (
    DATA_AXIS,
    SPEC_AXIS,
    make_mesh,
    world_sharding,
    shard_world,
    make_sharded_resim_fn,
    make_sharded_speculate_fn,
    make_sharded_canonical_fn,
)

__all__ = [
    "multihost",
    "DATA_AXIS",
    "SPEC_AXIS",
    "make_mesh",
    "world_sharding",
    "shard_world",
    "make_sharded_resim_fn",
    "make_sharded_speculate_fn",
    "make_sharded_canonical_fn",
]
