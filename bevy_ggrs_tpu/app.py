"""App — plugin assembly and registration (the ``GgrsPlugin``/``RollbackApp``
analog, /root/reference/src/lib.rs:200-260 + src/snapshot/rollback_app.rs).

Collects the rollback registry (components, resources, hierarchy, checksums,
strategies), the user step function (the ``GgrsSchedule`` contents), and the
simulation constants (players, fps, input spec), then lazily builds the
compiled device functions (advance / resim / speculate / checksum).

Determinism stance: the step function is a pure JAX function compiled once —
there is no scheduler to race, which is this framework's stronger version of
the reference forcing ``AdvanceWorld`` single-threaded and setting schedule
ambiguity detection to Error (lib.rs:236-246)."""

from __future__ import annotations

from functools import cached_property
from typing import Callable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .ops.resim import (
    StepCtx,
    make_advance_fn,
    make_canonical_branched_fn,
    make_canonical_resim_fn,
    make_packed_canonical_resim_fn,
    make_packed_resim_fn,
    make_packed_speculate_fn,
    make_resim_fn,
    make_speculate_fn,
)
from .snapshot.checksum import world_checksum
from .snapshot.strategy import CopyStrategy, Strategy
from .snapshot.world import Registry, WorldState

DEFAULT_FPS = 60  # /root/reference/src/lib.rs:62


class App:
    """Rollback application: registration surface + compiled device functions."""
    def __init__(
        self,
        num_players: int = 2,
        capacity: int = 1024,
        fps: int = DEFAULT_FPS,
        input_shape: Tuple[int, ...] = (),
        input_dtype=np.uint8,
        seed: int = 0,
        retention: int = 16,
        canonical_depth: "Optional[int]" = None,
        canonical_branches: "Optional[int]" = None,
    ):
        self.num_players = num_players
        self.fps = fps
        # despawn-retirement horizon (frames); must be >= the session's
        # max prediction window / check distance (see ops/resim.py docstring)
        self.retention = retention
        # bit-determinism mode: run EVERY advance through one fixed-length
        # compiled program (see ops/resim.resim_padded).  Required for float
        # sims whose peers must stay bit-identical under differing rollback
        # histories; None = per-length programs (fastest dispatch)
        self.canonical_depth = canonical_depth
        # canonical-branched mode: the single program is additionally vmapped
        # over a fixed number of branch lanes (lane 0 = real inputs, others =
        # speculative hedges or dummies).  Lets speculation coexist with
        # bit-determinism — but the (depth, branches) shape is then a
        # LOBBY-WIDE constant: every peer must dispatch the same program
        self.canonical_branches = canonical_branches
        if canonical_branches is not None and canonical_depth is None:
            raise ValueError("canonical_branches requires canonical_depth")
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.seed = seed
        self.reg = Registry(capacity)
        self._step: Optional[Callable] = None
        self._setup: Optional[Callable] = None

    # -- registration (RollbackApp surface) --------------------------------

    def rollback_component(
        self,
        name: str,
        shape=(),
        dtype=jnp.float32,
        default=None,
        checksum: bool = False,
        hash_fn=None,
        strategy: Strategy = CopyStrategy,
        required: bool = False,
    ) -> "App":
        """Register a component column for snapshot/rollback (RollbackApp analog)."""
        self.reg.register_component(
            name, shape, dtype, default, checksum, hash_fn, strategy, required
        )
        return self

    def rollback_resource(
        self,
        name: str,
        init,
        checksum: bool = False,
        hash_fn=None,
        present: bool = True,
        strategy: Strategy = CopyStrategy,
    ) -> "App":
        """Register a resource pytree for snapshot/rollback."""
        self.reg.register_resource(name, init, checksum, hash_fn, present, strategy)
        return self

    def checksum_component(self, name: str, hash_fn=None) -> "App":
        """Enable checksumming for an already-registered component
        (``checksum_component[_with_hash]``, rollback_app.rs:31-133)."""
        spec = self.reg.components[name]
        import dataclasses

        self.reg.components[name] = dataclasses.replace(
            spec, checksum=True, hash_fn=hash_fn or spec.hash_fn
        )
        return self

    def checksum_resource(self, name: str, hash_fn=None) -> "App":
        """Enable checksumming for an already-registered resource."""
        spec = self.reg.resources[name]
        import dataclasses

        self.reg.resources[name] = dataclasses.replace(
            spec, checksum=True, hash_fn=hash_fn or spec.hash_fn
        )
        return self

    def register_hierarchy(self) -> "App":
        """Enable the parent-link (ChildOf analog) component and recursive despawn."""
        self.reg.register_hierarchy()
        return self

    def set_step(self, fn: Callable[[WorldState, StepCtx], WorldState]) -> "App":
        """Set the simulation step (the user's ``GgrsSchedule`` systems)."""
        self._step = fn
        self._invalidate()
        return self

    def set_setup(self, fn: Callable[[WorldState], WorldState]) -> "App":
        """Optional world-setup function run once at session start."""
        self._setup = fn
        return self

    # -- state -------------------------------------------------------------

    def init_state(self) -> WorldState:
        """Build the initial WorldState (runs the setup function if set).

        Lossy snapshot strategies make the stored representation canonical
        (ops/resim.advance round-trips each frame); the INITIAL state gets
        the same store->load round-trip so the frame-0 snapshot restores
        exactly the state the first advance ran from."""
        w = self.reg.init_state()
        if self._setup is not None:
            w = self._setup(w)
        if not self.reg.is_identity_strategy():
            w = self.reg.load_state(self.reg.store_state(w))
        return w

    def zero_inputs(self) -> np.ndarray:
        return np.zeros((self.num_players, *self.input_shape), self.input_dtype)

    # -- compiled functions (lazy) ------------------------------------------

    @property
    def step(self):
        """The registered step function (raises if set_step was never called)."""
        if self._step is None:
            raise RuntimeError("App.set_step was never called")
        return self._step

    def _invalidate(self):
        for k in ("advance_fn", "resim_fn", "resim_fn_donated",
                  "speculate_fn", "checksum_fn", "branched_fn",
                  "packed_spec", "packed_resim_fn", "packed_resim_fn_donated",
                  "packed_speculate_fn"):
            self.__dict__.pop(k, None)

    @cached_property
    def advance_fn(self):
        """jit single-frame advance -> (state, checksum); routes through the
        canonical program when bit-determinism mode is configured."""
        if self.canonical_depth is not None:
            # route single advances through the SAME canonical program
            resim = self.resim_fn

            def fn(state, inputs, status, frame, _unused=None):
                # keep device arrays on device (no np.asarray pull)
                inputs = inputs if hasattr(inputs, "ndim") else np.asarray(inputs)
                status = status if hasattr(status, "ndim") else np.asarray(status)
                final, stacked, checks = resim(
                    state, inputs[None], status[None], frame - 1
                )
                return final, checks[0]

            return fn
        return make_advance_fn(self.reg, self.step, self.fps, self.seed, self.retention)

    @cached_property
    def branched_fn(self):
        """Raw canonical-branched program (canonical_branches mode):
        fn(state, inputs[B, K, P, ...], status[B, K, P], start_frame,
        n_real[B]) -> per-lane (final, stacked, checks)."""
        if self.canonical_branches is None:
            raise RuntimeError("App was not configured with canonical_branches")
        return make_canonical_branched_fn(
            self.reg, self.step, self.fps, self.seed, self.retention,
            self.canonical_depth, self.canonical_branches,
        )

    @cached_property
    def resim_fn(self):
        """jit k-frame resim -> (final, stacked, checksums); canonical modes
        route through the single fixed-shape program."""
        if self.canonical_branches is not None:
            return self._branched_resim_wrapper()
        if self.canonical_depth is not None:
            return make_canonical_resim_fn(
                self.reg, self.step, self.fps, self.seed, self.retention,
                self.canonical_depth,
            )
        return make_resim_fn(self.reg, self.step, self.fps, self.seed, self.retention)

    @cached_property
    def resim_fn_donated(self):
        """Donating variant of :attr:`resim_fn` — the input state's buffers
        are handed to XLA for in-place reuse and the passed state object is
        DEAD after the call.  Callers must prove nothing else references the
        state (the driver tracks this; see GgrsRunner._run_batch).

        ``None`` in BOTH canonical modes: ``jit(donate_argnums=...)`` is a
        DIFFERENT compiled executable than the plain one, and canonical mode
        exists precisely because two compiles of the same step may round
        differently (ops/resim.resim_padded docstring) — a driver that
        alternates donated/non-donated dispatches by runtime donatability
        would reintroduce the program-variant drift canonical mode removes.
        Donation is a fast-path for the default (per-length-program) mode
        only."""
        if self.canonical_branches is not None or self.canonical_depth is not None:
            return None
        return make_resim_fn(
            self.reg, self.step, self.fps, self.seed, self.retention,
            donate=True,
        )

    def _branched_resim_wrapper(self):
        """resim_fn facade over the branched program: lane 0 carries the real
        inputs, other lanes duplicate it (dummy hedges) so non-speculating
        peers dispatch the exact same program as speculating ones."""
        fn = self.branched_fn
        B, K = self.canonical_branches, self.canonical_depth

        def wrapped(state, inputs_seq, status_seq, start_frame, _unused=None):
            import jax as _jax
            import jax.numpy as _jnp

            from .ops.resim import pad_repeat_last

            k = inputs_seq.shape[0]
            if k > K:
                raise ValueError(
                    f"resim depth {k} exceeds canonical_depth {K}"
                )
            pad = K - k
            inputs_seq = pad_repeat_last(inputs_seq, pad)
            status_seq = pad_repeat_last(status_seq, pad)
            xp = _jnp if isinstance(inputs_seq, _jax.Array) else np
            ib = xp.broadcast_to(inputs_seq[None], (B, *inputs_seq.shape))
            sp = _jnp if isinstance(status_seq, _jax.Array) else np
            sb = sp.broadcast_to(status_seq[None], (B, *status_seq.shape))
            n_real = np.full((B,), k, np.int32)
            finals, stacked, checks = fn(state, ib, sb, start_frame, n_real)
            from .ops.resim import trim_frames
            from .snapshot.lazy import tree_index

            final0, (stacked0, checks0) = tree_index(
                (finals, trim_frames((stacked, checks), k, axis=1)), 0
            )
            return final0, stacked0, checks0

        return wrapped

    @cached_property
    def speculate_fn(self):
        return make_speculate_fn(self.reg, self.step, self.fps, self.seed, self.retention)

    # -- packed single-upload programs (ops/packing.py) ---------------------

    @cached_property
    def packed_spec(self):
        """Static packed-buffer layout for this app's input spec."""
        from .ops.packing import PackedSpec

        return PackedSpec.for_app(self)

    @cached_property
    def packed_resim_fn(self):
        """Single-upload resim: ``fn(state, packed int8[k+1, W]) ->
        (final, stacked, checks)`` — the dispatch-floor fix (inputs, status
        and start frame ride ONE int8 buffer, split in-program by a pure
        bitcast; docs/dispatch_floor.md).

        Canonical-depth apps get the fixed-shape packed program, which
        returns stacked/checks UNTRIMMED at ``canonical_depth`` rows (the
        driver tracks the real count).  ``None`` under
        ``canonical_branches``: the branched program keeps its own
        ``[B, K]`` upload shape and the driver falls back to the unpacked
        branched path."""
        if self.canonical_branches is not None:
            return None
        if self.canonical_depth is not None:
            return make_packed_canonical_resim_fn(
                self.reg, self.step, self.packed_spec, self.fps, self.seed,
                self.retention, self.canonical_depth,
            )
        return make_packed_resim_fn(
            self.reg, self.step, self.packed_spec, self.fps, self.seed,
            self.retention,
        )

    @cached_property
    def packed_resim_fn_donated(self):
        """Donating packed resim — same donation contract as
        :attr:`resim_fn_donated`, and ``None`` in both canonical modes for
        the same program-variant-drift rationale."""
        if self.canonical_branches is not None or self.canonical_depth is not None:
            return None
        return make_packed_resim_fn(
            self.reg, self.step, self.packed_spec, self.fps, self.seed,
            self.retention, donate=True,
        )

    @cached_property
    def packed_speculate_fn(self):
        """Single-upload speculation fan-out (``None`` in canonical modes —
        the runner refuses a plain speculation cache there anyway)."""
        if self.canonical_branches is not None or self.canonical_depth is not None:
            return None
        return make_packed_speculate_fn(
            self.reg, self.step, self.packed_spec, self.fps, self.seed,
            self.retention,
        )

    @cached_property
    def checksum_fn(self):
        """jit-compiled world checksum -> uint32[2]."""
        import jax

        return jax.jit(lambda w: world_checksum(self.reg, w))
