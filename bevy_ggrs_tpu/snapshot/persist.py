"""Disk persistence for world state — save/resume beyond the in-memory ring.

The reference keeps checkpoints only in memory (its ring IS the rollback
feature; "no disk persistence anywhere", SURVEY §5.4).  Here a WorldState is
a flat pytree of arrays, so durable checkpoints are nearly free; combined
with :mod:`..session.replay` they enable resume, golden-state regression
tests, desync bisection across builds — and live lobby migration between
fleet workers (:mod:`..fleet`), where a checkpoint crossing a host boundary
is the whole hand-off.

Determinism stance (v2 format): every checkpoint records the registry
*schema* — the ordered ``(leaf path, dtype, shape)`` rows plus a digest —
so a load against a drifted registry names the exact mismatched leaves
instead of reporting a bare count, and a dtype mismatch **fails loudly by
default**.  The old behavior (``jnp.asarray(arr, t.dtype)``) silently cast,
which changes bits: a float64-saved/float32-loaded world resumes on a
different trajectory and desyncs a migrated lobby against its control run.
Pass ``allow_cast=True`` only for offline tooling that knowingly converts.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from .world import Registry, WorldState

# v1: leaves + frame only.  v2 adds the schema rows/digest and the optional
# ``extra_*`` payload namespace; v1 files still load (minus the schema
# niceties — leaf-count mismatch is all v1 can diagnose).
_FORMAT_VERSION = 2
_V1 = 1


@dataclasses.dataclass(frozen=True)
class Checkpoint:
    """A loaded checkpoint: the world, its frame, and any extra payloads
    (e.g. a lobby's unsimulated input-queue tail — see fleet/lobby.py)."""

    world: WorldState
    frame: int
    extras: Dict[str, np.ndarray]


def _leaf_rows(template: WorldState) -> List[str]:
    """Ordered ``path:dtype:shape`` schema rows for a registry's world
    template — the names the mismatch diagnostics speak in."""
    flat, _ = jax.tree_util.tree_flatten_with_path(template)
    rows = []
    for path, leaf in flat:
        arr = np.asarray(leaf)
        rows.append(
            f"{jax.tree_util.keystr(path)}:{arr.dtype.name}:{tuple(arr.shape)}"
        )
    return rows


def registry_schema(reg: Registry) -> List[str]:
    """The registry's checkpoint schema: one ``path:dtype:shape`` row per
    world leaf, in flatten order.  Stable across runs (flatten order is
    registration order for the dict fields)."""
    return _leaf_rows(reg.init_state())


def schema_digest(reg: Registry) -> str:
    """sha256 hex digest of :func:`registry_schema` — the cheap "same
    registry?" handshake value recorded in every v2 checkpoint."""
    return hashlib.sha256(
        "\n".join(registry_schema(reg)).encode()
    ).hexdigest()


def save_world(
    path,
    reg: Registry,
    world: WorldState,
    frame: int = 0,
    extras: Optional[Dict[str, np.ndarray]] = None,
) -> None:
    """Serialize a WorldState (+frame) to a compressed .npz checkpoint.

    ``extras`` attaches named side arrays (stored under ``extra_<name>``):
    the fleet migration path uses them for the input-queue tail so a
    checkpoint is world + frame + pending inputs in ONE artifact.  ``path``
    may be a filename or any file-like object (``np.savez_compressed``
    contract), which is how checkpoints are built in memory for wire
    transfer."""
    leaves, treedef = jax.tree.flatten(world)
    schema = registry_schema(reg)
    payload = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    for name, arr in (extras or {}).items():
        if not name or not name.isidentifier():
            raise ValueError(f"extra name {name!r} must be an identifier")
        payload[f"extra_{name}"] = np.asarray(arr)
    np.savez_compressed(
        path,
        __version__=_FORMAT_VERSION,
        __frame__=frame,
        __n_leaves__=len(leaves),
        __schema__=np.array(json.dumps(schema)),
        __schema_digest__=np.array(
            hashlib.sha256("\n".join(schema).encode()).hexdigest()
        ),
        **payload,
    )


def _schema_mismatch_error(saved: List[str], want: List[str]) -> ValueError:
    """Name the drifted leaves, not just the count (the whole point of
    recording the schema)."""
    saved_set, want_set = set(saved), set(want)
    only_ckpt = sorted(saved_set - want_set)
    only_reg = sorted(want_set - saved_set)
    parts = ["checkpoint schema does not match the registry"]
    if only_ckpt:
        parts.append(f"checkpoint-only leaves: {only_ckpt}")
    if only_reg:
        parts.append(f"registry-only leaves: {only_reg}")
    if not only_ckpt and not only_reg:
        parts.append("same leaves, different order — registration order changed")
    parts.append("(registered types changed since the save?)")
    return ValueError("; ".join(parts))


def load_checkpoint(path, reg: Registry, allow_cast: bool = False) -> Checkpoint:
    """Load a checkpoint saved by :func:`save_world`, schema-checked.

    The registry must match the one that saved: v2 checkpoints carry the
    full schema, so any drift (added/removed/renamed component, changed
    dtype or shape) raises a ValueError naming the mismatched leaves.
    A dtype mismatch is a determinism hazard — the silently-cast world
    would change bits and desync a migrated lobby against an unmigrated
    control — so it fails loudly unless ``allow_cast=True``."""
    z = np.load(path, allow_pickle=False)
    version = int(z["__version__"])
    if version not in (_V1, _FORMAT_VERSION):
        raise ValueError(f"unsupported checkpoint version {version}")
    template = reg.init_state()
    t_leaves, treedef = jax.tree.flatten(template)
    want_schema = registry_schema(reg)
    n = int(z["__n_leaves__"])
    if version >= _FORMAT_VERSION:
        saved_schema = json.loads(str(z["__schema__"]))
        saved_digest = str(z["__schema_digest__"])
        digest = hashlib.sha256("\n".join(want_schema).encode()).hexdigest()
        if saved_digest != digest:
            dtype_only = _dtype_only_drift(saved_schema, want_schema)
            if not (dtype_only and allow_cast):
                raise _schema_mismatch_error(saved_schema, want_schema)
    elif n != len(t_leaves):
        raise ValueError(
            f"checkpoint has {n} leaves; registry expects {len(t_leaves)} "
            "(registered types changed?)"
        )
    leaves = []
    for i, t in enumerate(t_leaves):
        arr = z[f"leaf_{i}"]
        row = want_schema[i]
        name = row.split(":", 1)[0]
        if arr.shape != tuple(t.shape):
            raise ValueError(
                f"leaf {name} (#{i}) shape {arr.shape} != registry shape "
                f"{tuple(t.shape)}"
            )
        t_dtype = np.asarray(t).dtype
        if arr.dtype != t_dtype:
            if not allow_cast:
                raise ValueError(
                    f"leaf {name} (#{i}) dtype {arr.dtype.name} != registry "
                    f"dtype {t_dtype.name} — loading would silently change "
                    "bits and desync a resumed/migrated run; pass "
                    "allow_cast=True only if you mean to convert"
                )
            arr = arr.astype(t_dtype)
        leaves.append(jax.numpy.asarray(arr))
    extras = {
        k[len("extra_"):]: z[k] for k in z.files if k.startswith("extra_")
    }
    return Checkpoint(
        world=jax.tree.unflatten(treedef, leaves),
        frame=int(z["__frame__"]),
        extras=extras,
    )


def _dtype_only_drift(saved: List[str], want: List[str]) -> bool:
    """True when the two schemas differ ONLY in leaf dtypes (same paths and
    shapes, same order) — the one drift ``allow_cast=True`` may bridge."""
    if len(saved) != len(want):
        return False
    for s, w in zip(saved, want):
        sp = s.split(":")
        wp = w.split(":")
        if len(sp) != 3 or len(wp) != 3:
            return False
        if sp[0] != wp[0] or sp[2] != wp[2]:
            return False
    return True


def load_world(
    path, reg: Registry, allow_cast: bool = False
) -> Tuple[WorldState, int]:
    """Returns ``(world, frame)`` — thin wrapper over
    :func:`load_checkpoint` keeping the historical two-tuple signature."""
    ck = load_checkpoint(path, reg, allow_cast=allow_cast)
    return ck.world, ck.frame
