"""Disk persistence for world state — save/resume beyond the in-memory ring.

The reference keeps checkpoints only in memory (its ring IS the rollback
feature; "no disk persistence anywhere", SURVEY §5.4).  Here a WorldState is
a flat pytree of arrays, so durable checkpoints are nearly free; combined
with :mod:`..session.replay` they enable resume, golden-state regression
tests, and desync bisection across builds."""

from __future__ import annotations

from typing import Tuple

import jax
import numpy as np

from .world import Registry, WorldState

_FORMAT_VERSION = 1


def save_world(path: str, reg: Registry, world: WorldState, frame: int = 0) -> None:
    """Serialize a WorldState (+frame) to a compressed .npz checkpoint."""
    leaves, treedef = jax.tree.flatten(world)
    np.savez_compressed(
        path,
        __version__=_FORMAT_VERSION,
        __frame__=frame,
        __n_leaves__=len(leaves),
        **{f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)},
    )


def load_world(path: str, reg: Registry) -> Tuple[WorldState, int]:
    """Returns (world, frame).  The registry must match the one that saved
    (same registered components/resources — the treedef is reconstructed
    from ``reg.init_state()``)."""
    z = np.load(path, allow_pickle=False)
    if int(z["__version__"]) != _FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {z['__version__']}")
    template = reg.init_state()
    t_leaves, treedef = jax.tree.flatten(template)
    n = int(z["__n_leaves__"])
    if n != len(t_leaves):
        raise ValueError(
            f"checkpoint has {n} leaves; registry expects {len(t_leaves)} "
            "(registered types changed?)"
        )
    leaves = []
    for i, t in enumerate(t_leaves):
        arr = z[f"leaf_{i}"]
        if arr.shape != tuple(t.shape):
            raise ValueError(
                f"leaf {i} shape {arr.shape} != registry shape {tuple(t.shape)}"
            )
        leaves.append(jax.numpy.asarray(arr, t.dtype))
    return jax.tree.unflatten(treedef, leaves), int(z["__frame__"])
