"""Snapshot store/load strategies.

The reference's ``Strategy`` trait (/root/reference/src/snapshot/strategy.rs:22-40)
is a bijection contract between a live component and its stored form, with
``CopyStrategy``/``CloneStrategy``/``ReflectStrategy`` implementations.  In JAX
all values are immutable arrays, so Copy and Clone coincide (the identity) and
Reflect's dynamic-typing role is played by pytree flattening, which every
snapshot already gets for free.

The strategy slot stays useful on TPU for a different reason: transforming the
*stored* representation.  ``QuantizeStrategy`` keeps the ring in bf16/f16,
halving snapshot HBM footprint — the kind of store/load bijection-with-loss
tradeoff the trait was designed to express."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class Strategy:
    """Optional store/load transforms applied at snapshot push/restore.

    ``None`` means identity (no work at save/load time)."""

    store: Optional[Callable] = None
    load: Optional[Callable] = None


#: Identity — bitwise snapshot (CopyStrategy, strategy.rs:43-59).
CopyStrategy = Strategy()

#: Alias: value semantics make copy and clone identical here
#: (CloneStrategy, strategy.rs:62-83).
CloneStrategy = Strategy()

#: Alias: pytrees are the reflection layer (ReflectStrategy, strategy.rs:86-110).
ReflectStrategy = Strategy()


def QuantizeStrategy(stored_dtype=jnp.bfloat16) -> Strategy:
    """Store snapshots in a narrower dtype to cut ring HBM usage.

    Lossy vs an identity-strategy run, but deterministic AND checksum-safe:
    the stored representation is canonical — the advance pipeline
    round-trips the live state through store->load every frame
    (ops/resim.advance), so live and restored-from-snapshot passes are
    bit-identical (SyncTest-proven; without the round-trip the live pass
    would drift from the resim pass and mismatch by construction)."""
    return Strategy(
        store=lambda a: a.astype(stored_dtype),
        load=lambda a: a,  # re-cast to the live dtype happens in load_state
    )
