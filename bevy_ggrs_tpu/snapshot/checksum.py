"""Deterministic world checksums as pure integer array ops.

The reference computes, per registered type, a per-entity hash of (stable
RollbackOrdered index, component hash) XOR-folded across entities, re-hashed to
break cross-type commutativity, then XORs all parts into a ``Checksum``
resource (/root/reference/src/snapshot/component_checksum.rs:64-111,
checksum.rs:86-99).  It uses seahash for portability (snapshot/mod.rs:318-320)
— the checksum must compare equal across peers.

TPU equivalent: a murmur3-style multiply-rotate-xor mix over the bit pattern
of each entity row (two independent 32-bit streams -> one 64-bit checksum),
masked by liveness, reduced over the entity axis with *wrapping uint32
addition* instead of the reference's XOR: addition is equally commutative/
associative (entity-order and sharding independent — a plain ``psum`` on the
device mesh, exact for integers, where an XOR all-reduce is not universally
supported by collective backends), and it weakens the XOR blind spot the
reference documents (checksum.rs:91-93 — two equal parts cancel under XOR but
not under addition).  Cross-TYPE parts still combine by XOR (scalar,
replicated, no collective involved).  Everything is uint32 arithmetic, which
XLA evaluates bit-identically on CPU and TPU — so checksum parity across
backends holds whenever the underlying state bits match (for float simulation
math the bits themselves may differ across backends; see docs/determinism.md
and the reference's own cross-platform warning,
/root/reference/docs/debugging-desyncs.md:55).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import numpy as np

from .world import Registry, WorldState, active_mask

# numpy scalars, NOT jnp: pre-existing device arrays captured by a jitted
# function are passed as per-call parameter buffers (a measured ~4 ms/call
# slow path through the TPU tunnel); numpy scalars embed as XLA literals.
_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_SEED_HI = 0x9E3779B9
_SEED_LO = 0x85EBCA6B


def _rotl(x, r):
    return (x << r) | (x >> (32 - r))


def mix32(h, k):
    """One murmur3 round: fold lane ``k`` into state ``h`` (uint32 arrays)."""
    k = k * _C1
    k = _rotl(k, 15)
    k = k * _C2
    h = h ^ k
    h = _rotl(h, 13)
    return h * np.uint32(5) + np.uint32(0xE6546B64)


def fmix32(h):
    """murmur3 finalizer — avalanche."""
    h = h ^ (h >> 16)
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * np.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def to_u32_lanes(arr: jnp.ndarray) -> jnp.ndarray:
    """Bit-cast ``[N, ...]`` -> ``[N, L]`` uint32 lanes (exact, dtype-aware)."""
    n = arr.shape[0]
    flat = arr.reshape(n, -1)
    dt = flat.dtype
    if dt == jnp.float32:
        return jax.lax.bitcast_convert_type(flat, jnp.uint32)
    if dt in (jnp.int32, jnp.uint32):
        return flat.astype(jnp.uint32) if dt == jnp.int32 else flat
    if dt in (jnp.bfloat16, jnp.float16):
        return jax.lax.bitcast_convert_type(flat, jnp.uint16).astype(jnp.uint32)
    if dt == jnp.float64:
        u = jax.lax.bitcast_convert_type(flat, jnp.uint64)
        lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
        return jnp.concatenate([lo, hi], axis=-1)
    if dt in (jnp.int64, jnp.uint64):
        u = flat.astype(jnp.uint64)
        lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
        return jnp.concatenate([lo, hi], axis=-1)
    # bool / int8 / uint8 / int16 / uint16: widen exactly
    return flat.astype(jnp.uint32)


def _type_tag(name: str, seed: int) -> np.uint32:
    """Host-side stable tag per registered type name (FNV-1a over utf-8)."""
    h = 0x811C9DC5 ^ (seed & 0xFFFFFFFF)
    for b in name.encode():
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return np.uint32(h)


def _fold_rows(lanes: jnp.ndarray, seed: jnp.uint32) -> jnp.ndarray:
    """Hash each row of ``[N, L]`` lanes -> uint32[N]."""
    n, l = lanes.shape
    h = jnp.full((n,), seed, jnp.uint32)  # created during trace: embeds as literal
    for i in range(l):  # L is static and small
        h = mix32(h, lanes[:, i])
    return fmix32(h ^ jnp.uint32(l))


def _fold_scalars(values, seed: jnp.uint32) -> jnp.ndarray:
    h = jnp.asarray(seed, jnp.uint32)
    for v in values:
        h = mix32(h, jnp.asarray(v).astype(jnp.uint32))
    return fmix32(h)


def component_part(
    reg: Registry, w: WorldState, name: str, seed: int
) -> jnp.ndarray:
    """Checksum part for one component type (uint32 scalar).

    Per entity: mix(stable id, row bits); masked XOR over entities; re-hash
    with the type tag — the exact structure of component_checksum.rs:64-108
    (stable index, custom-or-default hash, XOR, commutativity break)."""
    spec = reg.components[name]
    tag = _type_tag(name, seed)
    col = w.comps[name]
    if spec.hash_fn is not None:
        lanes = spec.hash_fn(col)
        if lanes.ndim == 1:
            lanes = lanes[:, None]
        lanes = lanes.astype(jnp.uint32)
    else:
        lanes = to_u32_lanes(col)
    h = _fold_rows(lanes, tag)
    h = fmix32(mix32(h, w.rollback_id.astype(jnp.uint32)))
    mask = active_mask(w) & w.has[name]
    part = jnp.sum(jnp.where(mask, h, jnp.uint32(0)), dtype=jnp.uint32)
    return fmix32(part ^ tag)


def resource_part(reg: Registry, w: WorldState, name: str, seed: int) -> jnp.ndarray:
    """Checksum part for one resource (single hash, no entity loop —
    resource_checksum.rs:60-84); presence participates in the hash."""
    spec = reg.resources[name]
    tag = _type_tag("res:" + name, seed)
    if spec.hash_fn is not None:
        lanes = jnp.ravel(spec.hash_fn(w.res[name])).astype(jnp.uint32)
    else:
        leaves = jax.tree.leaves(w.res[name])
        lanes = jnp.concatenate(  # bgt: ignore[BGT071]: leaf count is fixed by the resource's registered pytree structure, not by array values
            [to_u32_lanes(jnp.atleast_1d(x)[None]).ravel() for x in leaves]
        )
    h = jnp.asarray(tag, jnp.uint32)
    h = mix32(h, w.res_present[name].astype(jnp.uint32))

    def body(i, h):
        return mix32(h, lanes[i])

    present_h = jax.lax.fori_loop(0, lanes.shape[0], body, h)
    h = jnp.where(w.res_present[name], present_h, h)
    return fmix32(h ^ tag)


def entity_part(w: WorldState, seed: int) -> jnp.ndarray:
    """Hash (active rollback-entity count, total-ever-spawned) — catches
    spawn/despawn divergence with no registered types
    (entity_checksum.rs:29-52)."""
    tag = _type_tag("__entities__", seed)
    cnt = jnp.sum(active_mask(w)).astype(jnp.uint32)
    return _fold_scalars([cnt, w.next_id], tag)


def world_checksum(reg: Registry, w: WorldState) -> jnp.ndarray:
    """Full checksum -> uint32[2] (hi, lo) device array.

    XOR of all parts (checksum.rs:88-99) over two independent 32-bit streams;
    convert with :func:`checksum_to_int` for the cross-peer comparable value."""
    out = []
    for seed in (_SEED_HI, _SEED_LO):
        part = entity_part(w, seed)
        for name, spec in reg.components.items():
            if spec.checksum:
                part = part ^ component_part(reg, w, name, seed)
        for name, spec in reg.resources.items():
            if spec.checksum:
                part = part ^ resource_part(reg, w, name, seed)
        out.append(part)
    return jnp.stack(out)  # bgt: ignore[BGT071]: one entry per checksum-enabled registry leaf — length is fixed at registration, never data-dependent


def checksum_to_int(cs) -> int:
    """uint32[2] (or a lazy ChecksumRef) -> python int (the 64-bit cross-peer
    checksum value).  Forcing a ref pulls every pending batch in one transfer
    (see snapshot/lazy.py)."""
    import numpy as np

    if hasattr(cs, "to_int"):
        return cs.to_int()
    a = np.asarray(cs, dtype=np.uint64)
    return int((a[0] << np.uint64(32)) | a[1])


def checksum_peek(cs) -> "int | None":
    """Non-blocking :func:`checksum_to_int`: the value if it can be read
    without stalling the host (landed async copy, host-backed array), else
    None.  The pipelined consume path — see snapshot/lazy.py."""
    import numpy as np

    if hasattr(cs, "peek"):
        return cs.peek()
    a = np.asarray(cs, dtype=np.uint64)
    return int((a[0] << np.uint64(32)) | a[1])
