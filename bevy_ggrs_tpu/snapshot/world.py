"""Columnar SoA world state + registry — the TPU-native ECS substrate.

The reference snapshots per-type ``HashMap<RollbackId, C>`` keyed by a stable
``RollbackId`` assigned on spawn (/root/reference/src/snapshot/rollback.rs:34-59),
reconciles live-vs-snapshot entity sets on load (src/snapshot/entity.rs:55-99),
and rewrites stale entity references through a ``RollbackEntityMap``
(src/snapshot/rollback_entity_map.rs).  Those mechanisms exist because host-ECS
entity ids are unstable across despawn/respawn.

This build inverts the layout: every registered component is a fixed-capacity
device-resident column ``[capacity, *shape]``, entity identity is (slot,
rollback_id), and a snapshot is the *entire* :class:`WorldState` pytree.
Restoring a snapshot restores the allocator, ids, masks, and columns wholesale,
so:

- entity reconciliation / respawn-with-same-id is automatic (slots are stable);
- ``RollbackEntityMap`` is the identity (slot indices stay valid) — the
  MapEntities pass (src/snapshot/component_map.rs) becomes a no-op by design;
- deferred-despawn markers behave exactly like the reference's
  ``RollbackDespawned`` disabling component (src/snapshot/despawn.rs): a marker
  set after frame F is absent from F's snapshot, so rolling back to F *is* the
  EntityResurrect pass.

Invariants preserved from the reference:

- ``rollback_id`` is assigned once per logical entity, monotonically — the
  ``RollbackOrdered`` never-forget insertion order (rollback.rs:62-99) is the id
  itself, giving checksums a stable per-entity index.
- despawn is deferred until the frame is confirmed
  (despawn.rs:89-112 -> :func:`despawn_confirmed`); marked entities are
  excluded from the active mask the way disabling components hide entities
  from queries (despawn.rs:114-143).
- spawn order is deterministic: first free slot, ids in call order.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .strategy import Strategy, CopyStrategy


@jax.tree_util.register_dataclass
@dataclass
class WorldState:
    """The complete rollback-visible simulation state (a JAX pytree).

    Everything here is restored wholesale on rollback.  Host-side state that
    must NOT roll back (render caches, etc.) simply lives outside this pytree
    — the analog of not registering a type for rollback.
    """

    comps: Dict[str, jnp.ndarray]  # name -> [capacity, *shape]
    has: Dict[str, jnp.ndarray]  # name -> bool[capacity] (entity has comp)
    res: Dict[str, Any]  # resource name -> pytree
    res_present: Dict[str, jnp.ndarray]  # name -> bool scalar
    alive: jnp.ndarray  # bool[capacity]
    rollback_id: jnp.ndarray  # int32[capacity]; -1 = free slot
    despawn_pending: jnp.ndarray  # bool[capacity]
    despawn_frame: jnp.ndarray  # int32[capacity] (valid iff pending)
    next_id: jnp.ndarray  # int32 scalar: total entities ever spawned
    overflow: jnp.ndarray  # bool scalar: a spawn found no free slot


def active_mask(w: WorldState) -> jnp.ndarray:
    """Alive and not marked for deferred despawn — what 'queries' see.

    Mirrors ``RollbackDespawned`` being a disabling component
    (/root/reference/src/snapshot/despawn.rs:114-129)."""
    return w.alive & ~w.despawn_pending


@dataclass(frozen=True)
class ComponentSpec:
    """Static registration record for one component column."""
    name: str
    shape: Tuple[int, ...]
    dtype: Any
    default: Any
    checksum: bool
    hash_fn: Optional[Callable[[jnp.ndarray], jnp.ndarray]]
    strategy: Strategy
    required: bool  # inserted on every spawn (cf. #[require(Rollback)] patterns)


@dataclass(frozen=True)
class ResourceSpec:
    """Static registration record for one resource."""
    name: str
    init: Any
    checksum: bool
    hash_fn: Optional[Callable[[Any], jnp.ndarray]]
    present: bool
    strategy: Strategy


class Registry:
    """Host-side static registration of rollback state.

    The analog of the ``RollbackApp`` extension-trait registration surface
    (/root/reference/src/snapshot/rollback_app.rs:31-133): components and
    resources opt in to snapshotting, checksumming (optionally with a custom
    hash), and a store/load strategy."""

    PARENT = "child_of"  # reserved hierarchy component (ChildOf analog)

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self.components: Dict[str, ComponentSpec] = {}
        self.resources: Dict[str, ResourceSpec] = {}

    # -- registration ------------------------------------------------------

    def register_component(
        self,
        name: str,
        shape: Tuple[int, ...] = (),
        dtype: Any = jnp.float32,
        default: Any = None,
        checksum: bool = False,
        hash_fn: Optional[Callable] = None,
        strategy: Strategy = CopyStrategy,
        required: bool = False,
    ) -> "Registry":
        """Register a fixed-shape component column (see RollbackApp surface notes)."""
        if name in self.components:
            raise ValueError(f"component {name!r} already registered")
        # defaults live as NUMPY values: registry-held device arrays captured
        # inside jitted spawn ops become per-call parameter buffers (measured
        # slow path on the TPU tunnel); numpy embeds as XLA literals
        np_dtype = np.dtype(jnp.dtype(dtype).name) if not isinstance(dtype, np.dtype) else dtype
        if default is None:
            default = np.zeros(shape, np_dtype)
        else:
            default = np.asarray(default, np_dtype)
            if default.shape != tuple(shape):
                raise ValueError(
                    f"default for {name!r} has shape {default.shape}, want {shape}"
                )
        self.components[name] = ComponentSpec(
            name, tuple(shape), dtype, default, checksum, hash_fn, strategy, required
        )
        return self

    def register_hierarchy(self) -> "Registry":
        """Register the parent-link component (``ChildOf`` analog).

        Parent references are slot indices; because snapshots restore the
        allocator wholesale, slots are stable and no parent remap is needed on
        rollback (cf. /root/reference/src/snapshot/childof_snapshot.rs, whose
        inline remap exists only because host-ECS ids are unstable)."""
        return self.register_component(
            self.PARENT, (), jnp.int32, default=np.int32(-1), checksum=True
        )

    @property
    def has_hierarchy(self) -> bool:
        return self.PARENT in self.components

    def register_resource(
        self,
        name: str,
        init: Any,
        checksum: bool = False,
        hash_fn: Optional[Callable] = None,
        present: bool = True,
        strategy: Strategy = CopyStrategy,
    ) -> "Registry":
        """Register a resource pytree (with optional initial absence)."""
        if name in self.resources:
            raise ValueError(f"resource {name!r} already registered")
        init = jax.tree.map(np.asarray, init)  # numpy: see register_component
        self.resources[name] = ResourceSpec(
            name, init, checksum, hash_fn, present, strategy
        )
        return self

    # -- state construction ------------------------------------------------

    def init_state(self) -> WorldState:
        """Allocate the empty fixed-capacity WorldState for this registry."""
        cap = self.capacity
        comps = {
            n: jnp.broadcast_to(s.default, (cap, *s.shape)).astype(s.dtype)
            for n, s in self.components.items()
        }
        has = {n: jnp.zeros((cap,), bool) for n in self.components}
        res = {n: jax.tree.map(jnp.asarray, s.init) for n, s in self.resources.items()}
        res_present = {
            n: jnp.asarray(s.present, bool) for n, s in self.resources.items()
        }
        return WorldState(
            comps=comps,
            has=has,
            res=res,
            res_present=res_present,
            alive=jnp.zeros((cap,), bool),
            rollback_id=jnp.full((cap,), -1, jnp.int32),
            despawn_pending=jnp.zeros((cap,), bool),
            despawn_frame=jnp.zeros((cap,), jnp.int32),
            next_id=jnp.int32(0),
            overflow=jnp.asarray(False),
        )

    # -- snapshot strategies ----------------------------------------------

    def store_state(self, w: WorldState) -> WorldState:
        """Apply per-type store strategies before a snapshot is retained.

        With all-Copy strategies this is the identity; a quantizing strategy
        (e.g. bf16 ring storage) halves snapshot HBM at store time — the
        TPU-meaningful analog of the reference's Copy/Clone/Reflect strategy
        choice (/root/reference/src/snapshot/strategy.rs:22-110)."""
        comps = dict(w.comps)
        for n, s in self.components.items():
            if s.strategy.store is not None:
                comps[n] = s.strategy.store(comps[n])
        res = dict(w.res)
        for n, s in self.resources.items():
            if s.strategy.store is not None:
                res[n] = jax.tree.map(s.strategy.store, res[n])
        return dataclasses.replace(w, comps=comps, res=res)

    def load_state(self, stored: WorldState) -> WorldState:
        """Inverse of :meth:`store_state` applied when a snapshot is restored."""
        comps = dict(stored.comps)
        for n, s in self.components.items():
            if s.strategy.load is not None:
                comps[n] = s.strategy.load(comps[n]).astype(s.dtype)
        res = dict(stored.res)
        for n, s in self.resources.items():
            if s.strategy.load is not None:
                res[n] = jax.tree.map(s.strategy.load, res[n])
        return dataclasses.replace(stored, comps=comps, res=res)

    def is_identity_strategy(self) -> bool:
        return all(
            s.strategy.store is None and s.strategy.load is None
            for s in list(self.components.values()) + list(self.resources.values())
        )


# ---------------------------------------------------------------------------
# Entity operations (all jit-traceable; Registry is static)
# ---------------------------------------------------------------------------


def spawn(
    reg: Registry, w: WorldState, comps: Optional[Dict[str, Any]] = None
) -> Tuple[WorldState, jnp.ndarray]:
    """Spawn one entity in the first free slot; returns (world, slot).

    Assigns the next monotonic rollback id — the on-add hook + RollbackOrdered
    push of the reference (/root/reference/src/snapshot/rollback.rs:45-59).
    If the world is full nothing is written (live entities are untouched), the
    ``overflow`` flag is set (checked host-side), and the returned slot is -1."""
    comps = comps or {}
    free = ~w.alive
    any_free = jnp.any(free)
    slot = jnp.argmax(free).astype(jnp.int32)  # first free slot (0 when full)

    def put(arr, value):
        # masked write: a full world must leave slot 0's live state intact
        return arr.at[slot].set(jnp.where(any_free, value, arr[slot]))

    new_comps = dict(w.comps)
    new_has = dict(w.has)
    for name, spec in reg.components.items():
        if name in comps:
            row = jnp.asarray(comps[name], spec.dtype)
            new_comps[name] = put(new_comps[name], row)
            new_has[name] = put(new_has[name], True)
        elif spec.required:
            new_comps[name] = put(new_comps[name], spec.default)
            new_has[name] = put(new_has[name], True)
        else:
            new_has[name] = put(new_has[name], False)
    unknown = set(comps) - set(reg.components)
    if unknown:
        raise KeyError(f"spawn with unregistered components: {sorted(unknown)}")
    return (
        dataclasses.replace(
            w,
            comps=new_comps,
            has=new_has,
            alive=put(w.alive, True),
            rollback_id=put(w.rollback_id, w.next_id),
            despawn_pending=put(w.despawn_pending, False),
            next_id=w.next_id + any_free.astype(w.next_id.dtype),
            overflow=w.overflow | ~any_free,
        ),
        jnp.where(any_free, slot, jnp.int32(-1)),
    )


def spawn_many(
    reg: Registry, w: WorldState, comps: Dict[str, jnp.ndarray], count
) -> WorldState:
    """Spawn up to ``rows`` entities at once (vectorized).

    ``comps`` maps names to ``[rows, *shape]`` arrays; ``count`` (traced scalar
    <= rows) limits how many actually spawn — the particles stress test spawns
    ``--rate`` per frame this way (/root/reference/examples/stress_tests/
    particles.rs:258-271).  Ids are assigned in row order; slots in ascending
    free-slot order, so the result is deterministic."""
    rows = next(iter(comps.values())).shape[0]
    count = jnp.minimum(jnp.asarray(count, jnp.int32), rows)
    free = ~w.alive
    rank = jnp.cumsum(free) - 1  # rank of each free slot among free slots
    take = free & (rank < count)
    n_taken = jnp.sum(take).astype(jnp.int32)
    # row index feeding each taken slot
    row_of_slot = jnp.where(take, rank, 0)
    new_comps = dict(w.comps)
    new_has = dict(w.has)
    for name, spec in reg.components.items():
        if name in comps:
            src = jnp.asarray(comps[name], spec.dtype)[row_of_slot]
            tk = take.reshape((-1,) + (1,) * len(spec.shape))
            new_comps[name] = jnp.where(tk, src, new_comps[name])
            new_has[name] = jnp.where(take, True, new_has[name])
        elif spec.required:
            tk = take.reshape((-1,) + (1,) * len(spec.shape))
            new_comps[name] = jnp.where(tk, spec.default, new_comps[name])
            new_has[name] = jnp.where(take, True, new_has[name])
        else:
            new_has[name] = jnp.where(take, False, new_has[name])
    ids = w.next_id + row_of_slot.astype(jnp.int32)
    return dataclasses.replace(
        w,
        comps=new_comps,
        has=new_has,
        alive=w.alive | take,
        rollback_id=jnp.where(take, ids, w.rollback_id),
        despawn_pending=jnp.where(take, False, w.despawn_pending),
        next_id=w.next_id + n_taken,
        overflow=w.overflow | (n_taken < count),
    )


def despawn(reg: Registry, w: WorldState, slot, frame) -> WorldState:
    """Mark ``slot`` for deferred despawn at ``frame``.

    The entity stays allocated (so a rollback before ``frame`` revives it —
    restoring the pre-mark snapshot IS the EntityResurrect pass,
    /root/reference/src/snapshot/despawn.rs:69-87) but is excluded from
    :func:`active_mask` immediately, like the disabling marker (:114-143)."""
    return dataclasses.replace(
        w,
        despawn_pending=w.despawn_pending.at[slot].set(True),
        despawn_frame=w.despawn_frame.at[slot].set(jnp.asarray(frame, jnp.int32)),
    )


def despawn_where(reg: Registry, w: WorldState, mask: jnp.ndarray, frame) -> WorldState:
    """Vectorized deferred despawn of every slot where ``mask`` (ttl expiry etc)."""
    mask = mask & w.alive
    return dataclasses.replace(
        w,
        despawn_pending=w.despawn_pending | mask,
        despawn_frame=jnp.where(mask, jnp.asarray(frame, jnp.int32), w.despawn_frame),
    )


def despawn_recursive(reg: Registry, w: WorldState, slot, frame) -> WorldState:
    """Deferred despawn of ``slot`` and all its descendants.

    Mirrors ``despawn_rollback``'s recursive marking including children
    (/root/reference/src/snapshot/despawn.rs:114-129).  Requires
    :meth:`Registry.register_hierarchy`."""
    if not reg.has_hierarchy:
        return despawn(reg, w, slot, frame)
    parent = w.comps[Registry.PARENT].astype(jnp.int32)
    has_parent = w.has[Registry.PARENT] & (parent >= 0)
    pidx = jnp.clip(parent, 0, reg.capacity - 1)
    init = jnp.zeros_like(w.alive).at[slot].set(True)

    def body(mark):
        prop = w.alive & has_parent & mark[pidx]
        return mark | prop

    def cond(carry):
        prev, cur = carry
        return jnp.any(prev != cur)

    def step(carry):
        _, cur = carry
        return cur, body(cur)

    _, mark = jax.lax.while_loop(cond, step, (jnp.zeros_like(init), init))
    return despawn_where(reg, w, mark, frame)


def despawn_confirmed(reg: Registry, w: WorldState, confirmed) -> WorldState:
    """Hard-free every slot whose despawn frame is confirmed.

    The ``AdvanceWorldSystems::DespawnConfirmed`` pass
    (/root/reference/src/snapshot/despawn.rs:89-112); wrapping i32 compare."""
    confirmed = jnp.asarray(confirmed, jnp.int32)
    kill = w.despawn_pending & ((w.despawn_frame - confirmed) <= 0)
    new_has = {n: h & ~kill for n, h in w.has.items()}
    return dataclasses.replace(
        w,
        has=new_has,
        alive=w.alive & ~kill,
        rollback_id=jnp.where(kill, -1, w.rollback_id),
        despawn_pending=w.despawn_pending & ~kill,
    )


# -- component / resource presence ops --------------------------------------


def insert_component(
    reg: Registry, w: WorldState, slot, name: str, value
) -> WorldState:
    """Give `slot` the component `name` with `value` (presence mask set)."""
    spec = reg.components[name]
    return dataclasses.replace(
        w,
        comps={**w.comps, name: w.comps[name].at[slot].set(jnp.asarray(value, spec.dtype))},
        has={**w.has, name: w.has[name].at[slot].set(True)},
    )


def remove_component(reg: Registry, w: WorldState, slot, name: str) -> WorldState:
    """Clear `slot`'s presence of component `name` (column value retained)."""
    return dataclasses.replace(
        w, has={**w.has, name: w.has[name].at[slot].set(False)}
    )


def insert_resource(reg: Registry, w: WorldState, name: str, value) -> WorldState:
    """Insert/overwrite a registered resource (present flag set).

    Mid-session insert/remove round-trips through rollback exactly like the
    reference's 4-case resource merge (/root/reference/src/snapshot/
    resource_snapshot.rs:82-98) because presence is part of the snapshot."""
    spec = reg.resources[name]
    value = jax.tree.map(
        lambda v, i: jnp.asarray(v, i.dtype), value, spec.init
    )
    return dataclasses.replace(
        w,
        res={**w.res, name: value},
        res_present={**w.res_present, name: jnp.asarray(True)},
    )


def remove_resource(reg: Registry, w: WorldState, name: str) -> WorldState:
    """Mark a registered resource absent (value retained for restore)."""
    return dataclasses.replace(
        w, res_present={**w.res_present, name: jnp.asarray(False)}
    )


def active_count(w: WorldState) -> jnp.ndarray:
    """Number of alive, not-despawn-pending entities."""
    return jnp.sum(active_mask(w)).astype(jnp.int32)
