"""Lazy device->host views — the driver's transfer-amortization layer.

The reference hands checksums to the session as plain integers because its
whole pipeline is host-side (/root/reference/src/schedule_systems.rs:223-237).
On TPU the checksum lives on device, and on high-latency links (the tunnel
this framework is benched through) every device->host pull costs a FLAT
round-trip (~tens of ms) regardless of payload size — while async dispatch
costs ~0.06 ms.  Measured on the bench TPU: one pull of 1 tiny array and one
pull of 32 arrays both cost ~70 ms; a second read of an already-pulled array
costs ~0.04 ms (jax caches the host copy per-Array).

Consequences, and the design here:

- :class:`BatchChecks` wraps one dispatch's stacked ``uint32[k, 2]`` checksum
  output and registers itself in a process-wide pending set.  Forcing ANY
  instance pulls EVERY pending instance in a single ``jax.device_get`` call —
  so the flat round-trip cost is paid once per *pull*, not once per frame.
- :class:`ChecksumRef` is a light (batch, row) handle used wherever the
  driver used to hold a per-frame device checksum; ``to_int()`` is the lazy
  provider the session protocols consume.
- :class:`LazySlice` defers ``stacked[i]`` materialization of per-frame saved
  states: the snapshot ring stores (stacked-buffer, index) handles and only
  issues the slicing dispatches for the one frame a rollback actually loads.

All of this is also correct (and nearly free) on CPU, where device_get is a
memcpy.
"""

from __future__ import annotations

import weakref
from typing import Optional

import numpy as np


class BatchChecks:
    """One dispatch's stacked checksums (uint32[k, 2] on device), pulled to
    host lazily and *collectively* (all pending instances in one transfer)."""

    _pending: "weakref.WeakSet[BatchChecks]" = weakref.WeakSet()

    __slots__ = ("_dev", "_host", "__weakref__")

    def __init__(self, dev):
        self._dev = dev
        self._host: Optional[np.ndarray] = None
        BatchChecks._pending.add(self)

    def host(self) -> np.ndarray:
        """uint64[k, 2] host copy; first call pulls every pending batch."""
        if self._host is None:
            BatchChecks.pull_pending()
        if self._host is None:  # defensive: a failed pull leaves us pending
            raise RuntimeError(
                "checksum batch was never pulled (a prior device->host "
                "transfer failed); retry pull_pending() once the backend "
                "is reachable"
            )
        return self._host

    def ref(self, i: int) -> "ChecksumRef":
        return ChecksumRef(self, i)

    @classmethod
    def pull_pending(cls) -> None:
        """Pull every unforced batch in ONE transfer.

        ``jax.device_get`` over a *list* issues one blocking round-trip per
        array (measured ~53 ms each on the tunnel); instead the pending
        batches are concatenated on device into a single ``[sum_k, 2]`` array
        (one async dispatch) and pulled as ONE array (one round-trip)."""
        import jax

        pending = [b for b in cls._pending if b._host is None]
        if not pending:
            cls._pending.clear()
            return
        # NOTE: batches leave the pending set only AFTER the pull succeeds —
        # if the device_get raises (flaky tunnel), every batch stays pending
        # and the next pull retries, instead of orphaning them with
        # _host=None and masking the device error with a TypeError later.
        if len(pending) == 1:
            pending[0]._host = np.asarray(
                jax.device_get(pending[0]._dev), dtype=np.uint64
            )
            cls._pending.clear()
            return
        fused = _concat_rows(*[b._dev for b in pending])
        host = np.asarray(jax.device_get(fused), dtype=np.uint64)
        off = 0
        for b in pending:
            k = b._dev.shape[0]
            b._host = host[off:off + k]
            off += k
        cls._pending.clear()


def _concat_rows(*xs):
    """Jitted [k_i, 2] -> [sum k_i, 2] concat (compiled once per shape tuple)."""
    import jax

    global _concat_rows_jit
    if _concat_rows_jit is None:
        import jax.numpy as jnp

        _concat_rows_jit = jax.jit(lambda *ys: jnp.concatenate(ys, axis=0))
    return _concat_rows_jit(*xs)


_concat_rows_jit = None


class ChecksumRef:
    """Handle to row ``i`` of a :class:`BatchChecks` — the per-frame checksum."""

    __slots__ = ("_batch", "_i")

    def __init__(self, batch: BatchChecks, i: int):
        self._batch = batch
        self._i = i

    def to_int(self) -> int:
        """The 64-bit cross-peer checksum value (forces the batched pull)."""
        a = self._batch.host()[self._i]
        return int((a[0] << np.uint64(32)) | a[1])

    def device(self):
        """Lazy uint32[2] device row (a dispatch, not a transfer)."""
        return self._batch._dev[self._i]

    def __array__(self, dtype=None, copy=None):
        a = self._batch.host()[self._i]
        return np.asarray(a, dtype=dtype if dtype is not None else np.uint64)


def wrap_single_checksum(cs) -> ChecksumRef:
    """Wrap a bare uint32[2] device checksum as a 1-row batch ref."""
    return BatchChecks(cs[None]).ref(0)


class LazySlice:
    """Deferred ``tree.map(a[i])`` over a stacked resim output — the ring
    stores these so per-frame save slicing never dispatches unless loaded.

    ``i`` may also be an ``(outer, inner)`` pair for doubly-stacked buffers
    (the BatchedRunner's ``[lobby, frame, ...]`` dispatch outputs)."""

    __slots__ = ("_stacked", "_i")

    def __init__(self, stacked, i):
        self._stacked = stacked
        self._i = i

    def materialize(self):
        """Slice the frame out of the stacked buffer (ONE jitted dispatch);
        the result no longer pins the parent buffer."""
        if isinstance(self._i, tuple):
            return tree_index2(self._stacked, *self._i)
        return tree_index(self._stacked, self._i)


def materialize(obj):
    """LazySlice -> concrete pytree; anything else passes through."""
    return obj.materialize() if isinstance(obj, LazySlice) else obj


def tree_index(stacked, i: int):
    """``tree.map(a[i])`` as ONE jitted dispatch.

    Eager per-leaf indexing costs one device op round-trip per leaf (~1 ms
    each through the tunnel); the jitted dynamic-index program slices every
    leaf in a single dispatch."""
    import jax

    global _tree_index_jit
    if _tree_index_jit is None:
        _tree_index_jit = jax.jit(
            lambda t, j: jax.tree.map(lambda a: a[j], t)
        )
    return _tree_index_jit(stacked, np.int32(i))


_tree_index_jit = None


def tree_index2(stacked, b: int, i: int):
    """``tree.map(a[b, i])`` as ONE jitted dispatch (doubly-stacked
    ``[lobby, frame, ...]`` buffers; see :func:`tree_index`)."""
    import jax

    global _tree_index2_jit
    if _tree_index2_jit is None:
        _tree_index2_jit = jax.jit(
            lambda t, bb, ii: jax.tree.map(lambda a: a[bb, ii], t)
        )
    return _tree_index2_jit(stacked, np.int32(b), np.int32(i))


_tree_index2_jit = None
