"""Lazy device->host views — the driver's transfer-amortization layer.

The reference hands checksums to the session as plain integers because its
whole pipeline is host-side (/root/reference/src/schedule_systems.rs:223-237).
On TPU the checksum lives on device, and on high-latency links (the tunnel
this framework is benched through) every device->host pull costs a FLAT
round-trip (~tens of ms) regardless of payload size — while async dispatch
costs ~0.06 ms.  Measured on the bench TPU: one pull of 1 tiny array and one
pull of 32 arrays both cost ~70 ms; a second read of an already-pulled array
costs ~0.04 ms (jax caches the host copy per-Array).

Consequences, and the design here:

- :class:`BatchChecks` wraps one dispatch's stacked ``uint32[k, 2]`` checksum
  output and registers itself in a process-wide pending set.  Forcing ANY
  instance pulls EVERY pending instance in a single ``jax.device_get`` call —
  so the flat round-trip cost is paid once per *pull*, not once per frame.
- :class:`ChecksumRef` is a light (batch, row) handle used wherever the
  driver used to hold a per-frame device checksum; ``to_int()`` is the lazy
  provider the session protocols consume.
- :class:`LazySlice` defers ``stacked[i]`` materialization of per-frame saved
  states: the snapshot ring stores (stacked-buffer, index) handles and only
  issues the slicing dispatches for the one frame a rollback actually loads.
- :class:`ReadbackQueue` (the pipelined tick engine's harvest side) starts a
  NON-blocking device->host copy per checksum batch at dispatch time
  (``jax.Array.copy_to_host_async``) and collects landed values on later
  ticks (``is_ready`` + cached host read) — so the per-frame ``send_checksum``
  path never blocks on the device.  Blocking pulls still exist, but only at
  flush points (``finish()``, ``set_session``, forensics) and as a GC-horizon
  backstop; each one is counted as a *forced* readback.

All of this is also correct (and nearly free) on CPU, where device_get is a
memcpy.
"""

from __future__ import annotations

import time
import weakref
from typing import Optional

import numpy as np

# Process-wide async-readback accounting.  Plain always-on counters (bench and
# tests read these without enabling telemetry); mirrored into the telemetry
# registry when it is enabled.
_stats = {"harvested": 0, "forced": 0, "blocked_seconds": 0.0}

# On the CPU backend device buffers ARE host memory: the staged
# copy_to_host_async is pure dispatch-path overhead (a profiler-wrapped jax
# call, ~60us/tick at small N) and np.asarray on a ready array is zero-copy,
# so the harvest path only needs is_ready().  Real accelerators keep the
# staged copy — that is what makes the later read non-blocking there.
_skip_staged_copy: Optional[bool] = None


def _staged_copy_needed() -> bool:
    global _skip_staged_copy
    if _skip_staged_copy is None:
        try:
            import jax
            _skip_staged_copy = jax.devices()[0].platform == "cpu"
        except Exception:  # pragma: no cover - no jax in stub-only tests
            _skip_staged_copy = True
    return not _skip_staged_copy


def readback_stats() -> dict:
    """Snapshot of {harvested, forced, blocked_seconds} since process start."""
    return dict(_stats)


def _note_readback(harvested: int = 0, forced: int = 0,
                   blocked_s: float = 0.0) -> None:
    _stats["harvested"] += harvested
    _stats["forced"] += forced
    _stats["blocked_seconds"] += blocked_s
    from .. import telemetry

    if harvested:
        telemetry.count("readback_harvested_total", harvested,
                        help="checksum readbacks collected without blocking "
                             "(async copy had landed)")
    if forced:
        telemetry.count("readback_forced_total", forced,
                        help="checksum readbacks that blocked the host "
                             "(flush points / sync mode)")
        # always-on black box: forced pulls are the pipeline's degrade
        # signal, so they earn a flight-ring entry even with telemetry off
        telemetry.flight_recorder().record(
            "forced_readback", n=forced,
            blocked_ms=round(blocked_s * 1e3, 3),
        )
    if blocked_s:
        telemetry.count("host_blocked_seconds", blocked_s,
                        help="host seconds spent blocked in device->host "
                             "checksum pulls")


class BatchChecks:
    """One dispatch's stacked checksums (uint32[k, 2] on device), pulled to
    host lazily and *collectively* (all pending instances in one transfer)."""

    _pending: "weakref.WeakSet[BatchChecks]" = weakref.WeakSet()

    __slots__ = ("_dev", "_host", "_async", "__weakref__")

    def __init__(self, dev):
        self._dev = dev
        self._host: Optional[np.ndarray] = None
        self._async = False
        BatchChecks._pending.add(self)

    def start_async(self) -> None:
        """Begin the non-blocking device->host copy for this batch.

        Called at dispatch time by the pipelined runner; by the time a
        session wants the value the transfer has usually landed and
        :meth:`try_host` is a cached read.  A batch whose checksums live
        SHARDED across a device mesh (the lobby-sharded wave executor,
        ops/batch.ShardedWaveExecutor) gets one non-blocking copy PER
        SHARD — each device's block starts moving independently, and the
        later harvest assembles the host array from the per-shard copies
        without ever serializing the devices against each other.  No-op on
        objects without the jax.Array async-copy surface (host-backed test
        stubs)."""
        if self._host is not None or self._async:
            return
        if not _staged_copy_needed():
            # CPU: harvest gates on is_ready() alone; adoption is zero-copy
            self._async = True
            return
        shards = self._shards()
        if shards is not None:
            # sharded checksums: one staged copy per device shard
            for s in shards:
                copy = getattr(s.data, "copy_to_host_async", None)
                if copy is not None:
                    copy()
            self._async = True
            return
        copy = getattr(self._dev, "copy_to_host_async", None)
        if copy is not None:
            copy()
            self._async = True

    def _shards(self):
        """The batch's addressable device shards when it is split across a
        mesh (>= 2 shards), else None (the single-device fast path)."""
        shards = getattr(self._dev, "addressable_shards", None)
        if shards is not None and len(shards) > 1:
            return shards
        return None

    def _transfer_landed(self) -> bool:
        """True when reading the device value would not block (for a
        sharded batch: every shard's copy has landed)."""
        shards = self._shards()
        if shards is not None:
            for s in shards:
                ready = getattr(s.data, "is_ready", None)
                if ready is not None and not ready():
                    return False
            return True
        ready = getattr(self._dev, "is_ready", None)
        return bool(ready()) if ready is not None else True

    def _adopt_host(self) -> None:
        """Take the completed transfer (cached host copy / host-backed
        array) without a meaningful block, and count the harvest."""
        self._host = np.asarray(self._dev, dtype=np.uint64)
        BatchChecks._pending.discard(self)
        _note_readback(harvested=1)

    def try_host(self) -> Optional[np.ndarray]:
        """Non-blocking :meth:`host`: the uint64[k, 2] copy if it can be had
        without stalling, else None.  Starts the async copy as a side effect
        so un-started batches converge even without a pipelining runner."""
        if self._host is not None:
            return self._host
        self.start_async()
        if self._async and not self._transfer_landed():
            return None
        self._adopt_host()
        return self._host

    def host(self) -> np.ndarray:
        """uint64[k, 2] host copy; first call pulls every pending batch."""
        if self._host is None:
            BatchChecks.pull_pending()
        if self._host is None:  # defensive: a failed pull leaves us pending
            raise RuntimeError(
                "checksum batch was never pulled (a prior device->host "
                "transfer failed); retry pull_pending() once the backend "
                "is reachable"
            )
        return self._host

    def ref(self, i: int) -> "ChecksumRef":
        return ChecksumRef(self, i)

    @classmethod
    def pull_pending(cls) -> None:
        """Pull every unforced batch in ONE transfer.

        ``jax.device_get`` over a *list* issues one blocking round-trip per
        array (measured ~53 ms each on the tunnel); instead the pending
        batches are concatenated on device into a single ``[sum_k, 2]`` array
        (one async dispatch) and pulled as ONE array (one round-trip)."""
        import jax

        pending = [b for b in cls._pending if b._host is None]
        if not pending:
            cls._pending.clear()
            return
        # Readback accounting: a pending batch whose async copy already
        # landed is a harvest (this pull won't wait on it); the rest are
        # forced (the host blocks until their dispatch completes).
        landed = sum(1 for b in pending if b._async and b._transfer_landed())
        t0 = time.perf_counter()
        # NOTE: batches leave the pending set only AFTER the pull succeeds —
        # if the device_get raises (flaky tunnel), every batch stays pending
        # and the next pull retries, instead of orphaning them with
        # _host=None and masking the device error with a TypeError later.
        if len(pending) == 1:
            pending[0]._host = np.asarray(
                jax.device_get(pending[0]._dev), dtype=np.uint64
            )
        else:
            fused = _concat_rows(*[b._dev for b in pending])
            host = np.asarray(jax.device_get(fused), dtype=np.uint64)
            off = 0
            for b in pending:
                k = b._dev.shape[0]
                b._host = host[off:off + k]
                off += k
        cls._pending.clear()
        _note_readback(harvested=landed, forced=len(pending) - landed,
                       blocked_s=time.perf_counter() - t0)


def _concat_rows(*xs):
    """Jitted [k_i, 2] -> [sum k_i, 2] concat (compiled once per shape tuple)."""
    import jax

    global _concat_rows_jit
    if _concat_rows_jit is None:
        import jax.numpy as jnp

        _concat_rows_jit = jax.jit(lambda *ys: jnp.concatenate(ys, axis=0))
    return _concat_rows_jit(*xs)


_concat_rows_jit = None


class ChecksumRef:
    """Handle to row ``i`` of a :class:`BatchChecks` — the per-frame checksum."""

    __slots__ = ("_batch", "_i")

    def __init__(self, batch: BatchChecks, i: int):
        self._batch = batch
        self._i = i

    def to_int(self) -> int:
        """The 64-bit cross-peer checksum value (forces the batched pull)."""
        a = self._batch.host()[self._i]
        return int((a[0] << np.uint64(32)) | a[1])

    # A ref IS the session's checksum provider: calling it forces (the flush
    # paths), peek() is the non-blocking read the pipelined desync driver
    # retries until the async copy lands.
    __call__ = to_int

    def peek(self) -> Optional[int]:
        """Non-blocking :meth:`to_int`: the value if the batched device->host
        copy has landed, else None (starting the copy if needed)."""
        h = self._batch.try_host()
        if h is None:
            return None
        a = h[self._i]
        return int((a[0] << np.uint64(32)) | a[1])

    def device(self):
        """Lazy uint32[2] device row (a dispatch, not a transfer)."""
        return self._batch._dev[self._i]

    def __array__(self, dtype=None, copy=None):
        a = self._batch.host()[self._i]
        return np.asarray(a, dtype=dtype if dtype is not None else np.uint64)


def wrap_single_checksum(cs) -> ChecksumRef:
    """Wrap a bare uint32[2] device checksum as a 1-row batch ref."""
    return BatchChecks(cs[None]).ref(0)


class ReadbackQueue:
    """The pipelined tick engine's readback coordinator.

    ``start(batch)`` begins a non-blocking device->host copy right after a
    dispatch; ``harvest()`` (called once per runner tick, and at the top of
    the sessions' compare paths) finalizes every batch whose copy has landed
    and async-starts any stragglers that entered the pending set some other
    way (``wrap_single_checksum``, spec-cache batches).  ``flush()`` is the
    blocking everything-now path for the existing flush points.

    The :class:`BatchChecks` process-wide pending set is the queue — there is
    no second registry to leak, and one queue instance serves every runner in
    the process (the batched pull already fuses across them anyway)."""

    def start(self, batch: BatchChecks) -> None:
        batch.start_async()

    def harvest(self) -> int:
        """Finalize landed transfers; returns how many were collected."""
        if not BatchChecks._pending:
            return 0
        n = 0
        for b in list(BatchChecks._pending):
            if b._host is not None:
                BatchChecks._pending.discard(b)
                continue
            if not b._async:
                b.start_async()
                if not b._async:
                    continue  # no async surface: leave for the forced path
            if b._transfer_landed():
                b._adopt_host()
                n += 1
        from .. import telemetry

        if telemetry.enabled():
            telemetry.gauge_set("pipeline_depth", float(self.depth()),
                                help="checksum dispatches in flight "
                                     "(async readbacks not yet landed)")
        return n

    def depth(self) -> int:
        """Batches still in flight (pending and unharvested)."""
        return sum(1 for b in BatchChecks._pending if b._host is None)

    def flush(self) -> None:
        """Blocking pull of everything still pending (flush points only;
        counted as forced readbacks unless the copies already landed)."""
        BatchChecks.pull_pending()


_readback_queue: Optional[ReadbackQueue] = None


def readback_queue() -> ReadbackQueue:
    """The process-wide :class:`ReadbackQueue` singleton."""
    global _readback_queue
    if _readback_queue is None:
        _readback_queue = ReadbackQueue()
    return _readback_queue


class LazySlice:
    """Deferred ``tree.map(a[i])`` over a stacked resim output — the ring
    stores these so per-frame save slicing never dispatches unless loaded.

    ``i`` may also be an ``(outer, inner)`` pair for doubly-stacked buffers
    (the BatchedRunner's ``[lobby, frame, ...]`` dispatch outputs)."""

    __slots__ = ("_stacked", "_i")

    def __init__(self, stacked, i):
        self._stacked = stacked
        self._i = i

    def materialize(self):
        """Slice the frame out of the stacked buffer (ONE jitted dispatch);
        the result no longer pins the parent buffer."""
        if isinstance(self._i, tuple):
            return tree_index2(self._stacked, *self._i)
        return tree_index(self._stacked, self._i)


def materialize(obj):
    """LazySlice -> concrete pytree; anything else passes through."""
    return obj.materialize() if isinstance(obj, LazySlice) else obj


def tree_index(stacked, i: int):
    """``tree.map(a[i])`` as ONE jitted dispatch.

    Eager per-leaf indexing costs one device op round-trip per leaf (~1 ms
    each through the tunnel); the jitted dynamic-index program slices every
    leaf in a single dispatch."""
    import jax

    global _tree_index_jit
    if _tree_index_jit is None:
        _tree_index_jit = jax.jit(
            lambda t, j: jax.tree.map(lambda a: a[j], t)
        )
    return _tree_index_jit(stacked, np.int32(i))


_tree_index_jit = None


def plan_row_gather(handles):
    """Group ``(target_row, snapshot)`` pairs by backing stacked buffer for
    one fused gather — the planning half of the BatchedRunner's mixed-source
    load/save paths.

    Each :class:`LazySlice` names a row of some stacked dispatch output —
    ``stacked[i]`` or ``stacked[b, i]``.  A wave where different lobbies load
    from DIFFERENT buffers (staggered rollbacks, partially-idle lobbies) used
    to fall back to one gather + one scatter dispatch per lobby; grouping the
    handles by ``id(buffer)`` turns the whole wave into one jitted program
    over a handful of source buffers (:func:`fused_load_rows` /
    :func:`fused_gather_rows`).

    Returns ``(groups, fallback)``: ``groups`` is a list of
    ``(buffer, lanes_i32[n], idxs_i32[n] | None, targets_i32[n])`` in
    first-seen order (deterministic given the handle order, which keeps the
    jit cache warm across ticks with the same wave shape); ``fallback``
    collects non-LazySlice snapshots for the caller's slow path."""
    by = {}
    order = []
    fallback = []
    for tgt, stored in handles:
        if not isinstance(stored, LazySlice):
            fallback.append((tgt, stored))
            continue
        if isinstance(stored._i, tuple):
            lane, idx = stored._i
        else:
            lane, idx = stored._i, None
        key = (id(stored._stacked), idx is None)
        g = by.get(key)
        if g is None:
            g = by[key] = (stored._stacked, [], [], [])
            order.append(key)
        g[1].append(lane)
        g[2].append(idx)
        g[3].append(tgt)
    groups = []
    for key in order:
        buf, lanes, idxs, tgts = by[key]
        groups.append((
            buf,
            np.asarray(lanes, np.int32),
            None if key[1] else np.asarray(idxs, np.int32),
            np.asarray(tgts, np.int32),
        ))
    return groups, fallback


_fused_load_jits: dict = {}


def _gather_group_rows(buf, lanes, idxs):
    import jax

    if idxs is None:
        return jax.tree.map(lambda a: a[lanes], buf)
    return jax.tree.map(lambda a: a[lanes, idxs], buf)


def fused_load_rows(worlds, groups, transform=None):
    """ONE jitted dispatch: gather rows out of several stacked source
    buffers and scatter them into the resident ``[M, ...]`` worlds at
    ``targets`` — the mixed-source batched load.

    ``groups`` comes from :func:`plan_row_gather`.  ``transform`` (optional)
    is vmapped over the gathered rows before the scatter — the non-identity
    snapshot strategies' ``load_state`` hook, fused into the same program.
    The jitted body is cached per ``transform`` object (hold a stable
    reference!) and re-traced by jax per group structure/shape, so
    steady-state wave shapes hit the cache."""
    import jax

    fn = _fused_load_jits.get(transform)
    if fn is None:

        def body(worlds, groups):
            for buf, lanes, idxs, targets in groups:
                rows = _gather_group_rows(buf, lanes, idxs)
                if transform is not None:
                    rows = jax.vmap(transform)(rows)
                worlds = jax.tree.map(
                    lambda w, r: w.at[targets].set(r), worlds, rows
                )
            return worlds

        fn = _fused_load_jits[transform] = jax.jit(body)
    return fn(worlds, tuple(groups))


_fused_gather_jits: dict = {}


def fused_gather_rows(groups, transform=None):
    """ONE jitted dispatch: gather rows from several stacked buffers into a
    fresh ``[n, ...]`` stack (group-concatenation order), optionally mapping
    ``transform`` over the rows (vmapped).

    The BatchedRunner's non-identity save path uses this to run
    ``store_state`` over every saved row of a wave in one dispatch instead
    of a per-lobby materialize loop; row ``j`` of the result backs a
    ``LazySlice(result, j)`` ring entry.  Output row order follows the
    groups' target arrays concatenated in order — callers map their logical
    indices through that permutation host-side (no device permute)."""
    import jax

    fn = _fused_gather_jits.get(transform)
    if fn is None:

        def body(groups):
            parts = [
                _gather_group_rows(buf, lanes, idxs)
                for buf, lanes, idxs, _t in groups
            ]
            if len(parts) == 1:
                rows = parts[0]
            else:
                import jax.numpy as jnp

                rows = jax.tree.map(
                    lambda *xs: jnp.concatenate(xs, axis=0), *parts
                )
            if transform is not None:
                rows = jax.vmap(transform)(rows)
            return rows

        fn = _fused_gather_jits[transform] = jax.jit(body)
    return fn(tuple(groups))


def tree_index2(stacked, b: int, i: int):
    """``tree.map(a[b, i])`` as ONE jitted dispatch (doubly-stacked
    ``[lobby, frame, ...]`` buffers; see :func:`tree_index`)."""
    import jax

    global _tree_index2_jit
    if _tree_index2_jit is None:
        _tree_index2_jit = jax.jit(
            lambda t, bb, ii: jax.tree.map(lambda a: a[bb, ii], t)
        )
    return _tree_index2_jit(stacked, np.int32(b), np.int32(i))


_tree_index2_jit = None
