"""Frame-indexed snapshot ring buffer.

TPU-native analog of ``GgrsSnapshots`` (/root/reference/src/snapshot/mod.rs:97-273).
The reference keeps one ring *per registered component type*, each a pair of
newest-first ``VecDeque``s (frames, snapshots).  Here a snapshot is the whole
world state — a pytree of device-resident SoA arrays — so ONE ring covers every
registered type, and push/rollback are O(1) host-side reference operations (the
arrays never leave the device).  Semantics preserved from the reference:

- ``set_depth`` trims oldest entries beyond depth (mod.rs:123-138); depth is
  synced to the max prediction window before every save (mod.rs:246-258).
- ``push`` evicts any stored frame >= the new frame under *wrapping* i32
  comparison (mod.rs:147-181, wraparound handling :159-163), then trims to depth.
- ``confirm(frame)`` prunes strictly-older frames (mod.rs:185-202).
- ``rollback(frame)`` discards newer entries until the target is at the front
  and raises if the target frame was never stored (mod.rs:210-226; the
  reference panics at :214).
- ``peek`` returns a stored snapshot without mutating the ring.

Unit-test parity: tests/test_ring.py ports the battery at mod.rs:369-512.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Generic, List, Optional, Sequence, Tuple, TypeVar

from ..utils.frames import frame_ge, frame_lt

T = TypeVar("T")


class MissingSnapshotError(KeyError):
    """Rollback target frame is not in the ring (reference panics, mod.rs:214)."""


class SnapshotRing(Generic[T]):
    """Newest-first ring of (frame, snapshot) pairs with wrapping-frame order."""

    def __init__(self, depth: int = 60):
        self._frames: Deque[int] = deque()
        self._snapshots: Deque[T] = deque()
        self._depth = depth
        # device-memory accounting (telemetry/devmem.py): owner + per-entry
        # byte estimate set by the driver; None keeps every ring op free
        self._devmem_owner: Optional[str] = None
        self._entry_bytes = 0

    def set_accounting(self, owner: Optional[str], entry_bytes: int) -> None:
        """Register this ring with the device-memory registry: every
        mutation re-notes ``len(ring) * entry_bytes`` under ``owner``
        (``entry_bytes`` = one stored world's device footprint — the
        driver computes it once per session; lazy-slice entries share
        their stacked buffer, so this is the materialized upper bound).
        ``owner=None`` turns accounting back off."""
        self._devmem_owner = owner
        self._entry_bytes = int(entry_bytes)
        if owner is not None:
            self._renote()

    def _renote(self) -> None:
        from ..telemetry import devmem

        devmem.note(self._devmem_owner, len(self._frames) * self._entry_bytes)

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._frames)

    @property
    def depth(self) -> int:
        return self._depth

    def frames(self) -> list[int]:
        """Stored frames, newest first."""
        return list(self._frames)

    # -- reference-parity operations --------------------------------------

    def set_depth(self, depth: int) -> None:
        """Resize; drops oldest entries if shrinking (mod.rs:123-138)."""
        self._depth = depth
        while len(self._frames) > self._depth:
            self._frames.pop()
            self._snapshots.pop()
        if self._devmem_owner is not None:
            self._renote()

    def push(self, frame: int, snapshot: T) -> None:
        """Store ``snapshot`` for ``frame``, evicting stored frames that are
        not older than it (wrapping compare), then trimming to depth."""
        while self._frames and frame_ge(self._frames[0], frame):
            self._frames.popleft()
            self._snapshots.popleft()
        self._frames.appendleft(frame)
        self._snapshots.appendleft(snapshot)
        while len(self._frames) > self._depth:
            self._frames.pop()
            self._snapshots.pop()
        if self._devmem_owner is not None:
            self._renote()

    def confirm(self, frame: int) -> None:
        """Drop snapshots strictly older than the confirmed frame
        (mod.rs:185-202); keeps ``frame`` itself so it can still be loaded."""
        while self._frames and frame_lt(self._frames[-1], frame):
            self._frames.pop()
            self._snapshots.pop()
        if self._devmem_owner is not None:
            self._renote()

    def rollback(self, frame: int) -> T:
        """Discard entries newer than ``frame``; return its snapshot.

        Raises :class:`MissingSnapshotError` if the frame is absent."""
        while self._frames:
            if self._frames[0] == frame:
                if self._devmem_owner is not None:
                    self._renote()
                return self._snapshots[0]
            self._frames.popleft()
            self._snapshots.popleft()
        raise MissingSnapshotError(
            f"rollback target frame {frame} not in snapshot ring"
        )

    def peek(self, frame: int) -> Optional[T]:
        """Return the snapshot for ``frame`` without mutating, or None."""
        for f, s in zip(self._frames, self._snapshots):
            if f == frame:
                return s
        return None

    def latest(self) -> Optional[T]:
        return self._snapshots[0] if self._snapshots else None

    def latest_frame(self) -> Optional[int]:
        return self._frames[0] if self._frames else None

    def clear(self) -> None:
        """Drop every stored snapshot."""
        self._frames.clear()
        self._snapshots.clear()
        if self._devmem_owner is not None:
            self._renote()


def rollback_many(
    rings: Sequence["SnapshotRing[T]"], targets: Sequence[Tuple[int, int]]
) -> List[Tuple[int, T]]:
    """Batched rollback across a server's per-lobby rings.

    ``targets`` is ``[(ring_index, frame), ...]``; each named ring performs
    its normal :meth:`SnapshotRing.rollback` (discarding newer entries,
    raising :class:`MissingSnapshotError` on absence) and the stored
    snapshots come back as ``[(ring_index, snapshot), ...]`` in target order
    — the input :func:`..snapshot.lazy.plan_row_gather` groups into one
    fused device gather for the BatchedRunner's mixed-source load wave."""
    return [(i, rings[i].rollback(f)) for i, f in targets]
