"""P2PSession — rollback netcode over a non-blocking socket.

The ggrs-core P2P surface reconstructed in SURVEY §2.3:
``poll_remote_clients`` drains the socket and drives per-peer protocol state;
``advance_frame`` decides save/rollback/advance and returns the request
stream; ``frames_ahead`` drives run-slow; events surface network lifecycle
and desyncs.  Frame semantics: the input added at frame f (after input
delay) governs the f -> f+1 transition; a mispredicted remote input at frame
F invalidates states > F, so the session requests Load(F) then
(Advance, Save) x (current - F) — which the driver fuses into one device
call (docs/architecture.md:21 request shapes)."""

from __future__ import annotations

import random
from typing import Any, Dict, List, Tuple

import numpy as np

from .. import telemetry
from ..utils.frames import (
    NULL_FRAME,
    frame_add,
    frame_diff,
    frame_ge,
    frame_gt,
    frame_le,
    frame_lt,
    frame_min,
)
from .events import (
    DesyncDetected,
    DesyncDetection,
    Disconnected,
    InputStatus,
    InvalidRequestError,
    NetworkStats,
    NotSynchronizedError,
    Player,
    PlayerType,
    PredictionThresholdError,
    SessionState,
)
from .input_queue import InputQueue
from .protocol import PeerEndpoint, now_s
from .requests import (
    AdvanceRequest,
    LoadRequest,
    RollbackCause,
    SaveCell,
    SaveRequest,
)


# absolute bound on un-acked send history (frames; ~68 s at 60 fps).  The
# ack-driven trim below normally keeps these lists tiny, and a peer that acks
# nothing eventually hits the disconnect timeout — but a peer whose
# *keepalives* arrive while its acks are lost one-way would otherwise defeat
# that timeout and grow the history without bound.  Oldest frames drop first;
# a peer that far behind has lost the stream anyway.
MAX_UNACKED_FRAMES = 4096
# how long an adopted disconnect-consensus frame keeps rebroadcasting
# (notices ride lossy transports; receipt is idempotent under the min rule)
DISC_NOTICE_REBROADCAST_S = 1.5


def _min_ack(endpoints):
    """Oldest last-acked frame across CONNECTED endpoints.

    Returns ``None`` when no connected endpoint remains (pending history can
    be dropped entirely), ``NULL_FRAME`` when some connected endpoint has not
    acked anything yet (nothing may be trimmed — a still-syncing peer or
    spectator must be able to receive the stream from its base), else the
    wraparound-safe minimum ack."""
    acked = None
    for ep in endpoints:
        if ep.disconnected:
            continue
        if ep.last_acked == NULL_FRAME:
            return NULL_FRAME
        acked = ep.last_acked if acked is None else frame_min(acked, ep.last_acked)
    return acked


class P2PSession:
    """Python-core P2P session (see module docstring for semantics)."""
    def __init__(
        self,
        num_players: int,
        players: List[Player],
        socket,
        input_shape=(),
        input_dtype=np.uint8,
        max_prediction: int = 8,
        input_delay: int = 0,
        desync_detection: DesyncDetection = DesyncDetection.OFF,
        disconnect_timeout_s: float = 2.0,
        disconnect_notify_start_s: float = 0.5,
        input_predictor=None,
        eager_checksums: bool = False,
    ):
        self._num_players = num_players
        self.socket = socket
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.input_size = int(np.prod(self.input_shape, dtype=int) or 1) * self.input_dtype.itemsize
        self._max_prediction = max_prediction
        self.input_delay = input_delay
        self.desync_detection = desync_detection
        # eager_checksums=True forces every local checksum provider at the
        # tick its frame confirms (the pre-pipeline synchronous behavior;
        # the bench's sync baseline).  Default off: providers are peeked
        # non-blocking each poll and published once the async device->host
        # copy lands — the protocol already tolerates checksums arriving
        # k frames late (docs/architecture.md "Tick pipeline").
        self.eager_checksums = bool(eager_checksums)
        self.current_frame = 0
        self._confirmed = NULL_FRAME
        self.events_buf: List = []
        self._staged: Dict[int, np.ndarray] = {}
        self._disc_corrected: set = set()  # addrs whose disconnect was resolved
        # disconnect-frame consensus (GGPO-style): handle -> last frame whose
        # REAL input stays in the sim; later frames bake DISCONNECTED/zero.
        # Adopted as the MINIMUM of local knowledge and every received
        # T_DISC_NOTICE so all survivors bake identical inputs for the dead
        # player.  _disc_notices rebroadcasts our adopted value for a short
        # window (notices ride lossy transports).
        self._disc_frame: Dict[int, int] = {}
        self._disc_notices: Dict[int, tuple] = {}  # handle -> (frame, until)

        self.local_handles: List[int] = []
        self.remote_handle_addr: Dict[int, Any] = {}
        self.spectator_addrs: List[Any] = []
        for p in players:
            if p.kind == PlayerType.LOCAL:
                self.local_handles.append(p.handle)
            elif p.kind == PlayerType.REMOTE:
                self.remote_handle_addr[p.handle] = p.address
            else:
                self.spectator_addrs.append(p.address)
        # wire rows pack local inputs in ascending-handle order and the
        # receiver unpacks the same way — sort so add_player order is free
        self.local_handles.sort()

        self.queues: Dict[int, InputQueue] = {
            h: InputQueue(self.input_shape, self.input_dtype,
                          delay=input_delay if h in self.local_handles else 0,
                          predictor=input_predictor)
            for h in range(num_players)
        }

        self._handle_of_addr: Dict[Any, List[int]] = {}
        for h, a in self.remote_handle_addr.items():
            self._handle_of_addr.setdefault(a, []).append(h)
        for a in self._handle_of_addr:
            self._handle_of_addr[a].sort()

        self.endpoints: Dict[Any, PeerEndpoint] = {}
        # bgt: ignore[BGT041]: handshake nonce — MUST differ across processes
        # so a restarted peer at the same addr is detected; host-side protocol
        # state only, never enters the simulation
        rng = random.Random(id(self) ^ random.getrandbits(32))
        peer_addrs = sorted(
            {a for a in self.remote_handle_addr.values()}, key=repr
        )
        for addr in peer_addrs:
            ep = PeerEndpoint(
                send=(lambda data, a=addr: self.socket.send_to(data, a)),
                # the peer streams THEIR local inputs: one row per handle they own
                input_size=self.input_size * len(self._handle_of_addr[addr]),
                rng_nonce=rng.getrandbits(32),
                disconnect_timeout_s=disconnect_timeout_s,
                disconnect_notify_start_s=disconnect_notify_start_s,
                addr=addr,
            )
            ep.on_input = self._make_on_input(addr)
            ep.on_checksum = self._make_on_checksum(addr)
            ep.on_stream_base = self._make_on_stream_base(addr)
            ep.on_disc_notice = self._make_on_disc_notice(addr)
            self.endpoints[addr] = ep
        # spectator endpoints: we stream all-player confirmed inputs to them
        self.spectator_endpoints: Dict[Any, PeerEndpoint] = {}
        for addr in self.spectator_addrs:
            ep = PeerEndpoint(
                send=(lambda data, a=addr: self.socket.send_to(data, a)),
                # full row: all-player inputs + one status byte per player
                input_size=self.input_size * num_players + num_players,
                rng_nonce=rng.getrandbits(32),
                disconnect_timeout_s=disconnect_timeout_s,
                disconnect_notify_start_s=disconnect_notify_start_s,
                addr=addr,
            )
            self.spectator_endpoints[addr] = ep
        # local input bytes pending ack, per remote peer: [(frame, bytes)]
        self._local_sent: List[Tuple[int, bytes]] = []
        # confirmed-input packets pending for spectators
        self._spectator_sent: List[Tuple[int, bytes]] = []
        self._next_spectator_frame = 0
        # desync bookkeeping: frame -> checksum provider / forced value
        self._local_checksums: Dict[int, Any] = {}
        self._remote_checksums: Dict[Tuple[Any, int], int] = {}

    # -- GGRS session surface ----------------------------------------------

    def num_players(self) -> int:
        return self._num_players

    def max_prediction(self) -> int:
        return self._max_prediction

    def rollback_window(self) -> int:
        """Deepest rollback this session can request (= the prediction
        window: a misprediction older than it would have stalled first)."""
        return self._max_prediction

    def confirmed_frame(self) -> int:
        return self._confirmed

    def local_player_handles(self) -> List[int]:
        return list(self.local_handles)

    def current_state(self) -> SessionState:
        """SYNCHRONIZING until every connected endpoint finished its handshake."""
        eps = list(self.endpoints.values()) + list(self.spectator_endpoints.values())
        if all(ep.state == SessionState.RUNNING or ep.disconnected for ep in eps):
            return SessionState.RUNNING
        return SessionState.SYNCHRONIZING

    def frames_ahead(self) -> int:
        """Smoothed frames-ahead estimate driving run-slow.

        Endpoints still warming up contribute 0: run-slow must not chase
        the one-sided seed estimate (half local-only data) — that estimate
        exists for the ``frame_advantage``/``time_sync_warmup`` gauges
        (telemetry/netstats.py), not for the scheduler."""
        vals = [
            ep.time_sync.frames_ahead()
            for ep in self.endpoints.values()
            if not ep.disconnected and ep.time_sync.warmed_up()
        ]
        return max(vals) if vals else 0

    def events(self):
        """Drain pending session events."""
        out, self.events_buf = self.events_buf, []
        return out

    def remote_player_handles(self) -> List[int]:
        """Handles owned by remote peers, ascending (the sampler's walk
        order — see telemetry/netstats.py)."""
        return sorted(self.remote_handle_addr)

    def network_stats(self, handle: int) -> NetworkStats:
        """Ping/queue/kbps/frames-behind for a remote handle.

        Local, unknown, spectator, and disconnected handles return a zeroed
        snapshot with ``is_live=False`` instead of raising, so periodic
        samplers can walk every handle without exception churn or log spam."""
        addr = self.remote_handle_addr.get(handle)
        if addr is None or addr not in self.endpoints:
            return NetworkStats(is_live=False)
        ep = self.endpoints[addr]
        if ep.disconnected:
            return NetworkStats(is_live=False)
        return ep.stats()

    def time_sync_for(self, handle: int):
        """The :class:`~bevy_ggrs_tpu.session.time_sync.TimeSync` tracker
        behind a remote handle, or None for non-live handles (the sampler's
        per-peer frame-advantage / warm-up source)."""
        addr = self.remote_handle_addr.get(handle)
        if addr is None or addr not in self.endpoints:
            return None
        ep = self.endpoints[addr]
        return None if ep.disconnected else ep.time_sync

    # -- polling ------------------------------------------------------------

    def poll_remote_clients(self) -> None:
        """Drain the socket, drive protocol timers, surface events
        (the process/network boundary, SURVEY §3.1)."""
        for addr, data in self.socket.receive_all():
            ep = self.endpoints.get(addr) or self.spectator_endpoints.get(addr)
            if ep is not None:
                ep.handle(data)
        all_eps = list(self.endpoints.values()) + list(self.spectator_endpoints.values())
        for ep in all_eps:
            ep.local_advantage = self._local_advantage(ep)
            ep.poll()
            self.events_buf.extend(ep.events)
            ep.events.clear()
        for addr, ep in self.endpoints.items():
            if ep.disconnected and addr not in self._disc_corrected:
                self._disc_corrected.add(addr)
                self._force_disconnect_correction(addr)
        if self._disc_notices:
            now = now_s()
            for h in list(self._disc_notices):
                f, until = self._disc_notices[h]
                if now >= until:
                    del self._disc_notices[h]
                    continue
                for ep in self.endpoints.values():
                    if not ep.disconnected and ep.state == SessionState.RUNNING:
                        ep.send_disc_notice(h, f)
        # retransmit un-acked local inputs + acks
        for ep in self.endpoints.values():
            if ep.state == SessionState.RUNNING and not ep.disconnected:
                ep.send_inputs(self._local_sent)
        for ep in self.spectator_endpoints.values():
            if ep.state == SessionState.RUNNING and not ep.disconnected:
                ep.send_inputs(self._spectator_sent)
        self._drive_desync_detection()

    def _local_advantage(self, ep: PeerEndpoint) -> int:
        if ep.last_received_frame == NULL_FRAME:
            return 0
        adv = self.current_frame - ep.last_received_frame
        ep.time_sync.note_local(self.current_frame, ep.last_received_frame)
        return adv

    def _make_on_input(self, addr):
        def cb(frame: int, raw: bytes) -> None:
            hs = self._handle_of_addr[addr]
            for i, h in enumerate(hs):
                chunk = raw[i * self.input_size:(i + 1) * self.input_size]
                value = np.frombuffer(chunk, self.input_dtype).reshape(
                    self.input_shape
                )
                self.queues[h].add_remote(frame, value)

        return cb

    def _make_on_stream_base(self, addr):
        def cb(base: int) -> None:
            for h in self._handle_of_addr[addr]:
                self.queues[h].set_base(base)

        return cb

    def _make_on_checksum(self, addr):
        def cb(frame: int, checksum: int) -> None:
            self._remote_checksums[(addr, frame)] = checksum

        return cb

    # -- advancing ----------------------------------------------------------

    def add_local_input(self, handle: int, value) -> None:
        """Stage this tick's input for a local handle."""
        if handle not in self.local_handles:
            raise InvalidRequestError(f"handle {handle} is not local")
        if self.current_state() != SessionState.RUNNING:
            raise NotSynchronizedError()
        self._staged[handle] = np.asarray(value, self.input_dtype).reshape(
            self.input_shape
        )

    def advance_frame(self) -> List:
        """Decide save/rollback/advance; returns the request stream."""
        if self.current_state() != SessionState.RUNNING:
            raise NotSynchronizedError()
        missing = set(self.local_handles) - set(self._staged)
        if missing:
            raise InvalidRequestError(f"missing local input for {sorted(missing)}")

        # stall check BEFORE consuming inputs, so the tick can retry.
        # confirmed must NOT advance past a pending mispredicted frame: the
        # rollback target has to stay in the driver's snapshot ring (a late
        # redundant input batch can otherwise leapfrog it)
        new_confirmed = self._compute_confirmed()
        pending_fi = NULL_FRAME
        for q in self.queues.values():
            f = q.first_incorrect
            if f != NULL_FRAME and (
                pending_fi == NULL_FRAME or frame_lt(f, pending_fi)
            ):
                pending_fi = f
        if pending_fi != NULL_FRAME:
            new_confirmed = frame_min(new_confirmed, pending_fi)
        if frame_diff(self.current_frame, new_confirmed) > self._max_prediction:
            self._staged.clear()
            raise PredictionThresholdError()

        # commit local inputs (delay applied by the queue) + broadcast
        eff_frames = {}
        for h in self.local_handles:
            eff_frames[h] = self.queues[h].add_local(
                self.current_frame, self._staged[h]
            )
        self._staged.clear()
        eff = eff_frames[self.local_handles[0]] if self.local_handles else None
        if eff is not None:
            raw = b"".join(
                np.ascontiguousarray(
                    self.queues[h].confirmed_input(eff)
                ).tobytes()
                for h in self.local_handles
            )
            self._local_sent.append((eff, raw))
            # flow-correlation anchor: a remote peer's rollback blaming
            # (handle, frame) pairs with this send in the merged Chrome
            # trace (telemetry/trace.py — one arrow from cause to effect)
            telemetry.record(
                "input_send", frame=eff, handles=list(self.local_handles),
                size=len(raw),
            )
            for ep in self.endpoints.values():
                if ep.state == SessionState.RUNNING and not ep.disconnected:
                    ep.send_inputs(self._local_sent)

        requests: List = []

        # rollback on misprediction — tracking WHOSE queue owns the earliest
        # incorrect frame, so the LoadRequest carries the blamed handle
        # (rollback-cause attribution; docs/observability.md "Network & QoS")
        first_incorrect = NULL_FRAME
        blamed_handle = None
        blamed_mismatch = False
        for h, q in self.queues.items():
            f = q.take_first_incorrect()
            if f != NULL_FRAME and (
                first_incorrect == NULL_FRAME or frame_lt(f, first_incorrect)
            ):
                first_incorrect = f
                blamed_handle = h
                blamed_mismatch = q.first_incorrect_mismatch
        rolled_back = False
        if first_incorrect != NULL_FRAME and frame_lt(
            first_incorrect, self.current_frame
        ):
            requests.append(LoadRequest(first_incorrect, cause=RollbackCause(
                handle=blamed_handle,
                frame=first_incorrect,
                lateness=frame_diff(self.current_frame, first_incorrect),
                mismatch=blamed_mismatch,
                kind="misprediction" if blamed_mismatch else "disconnect",
            )))
            i = first_incorrect
            while i != self.current_frame:
                inputs, status = self._inputs_for(i)
                requests.append(AdvanceRequest(inputs, status))
                requests.append(SaveRequest(frame_add(i, 1), SaveCell(self, frame_add(i, 1))))
                i = frame_add(i, 1)
            rolled_back = True

        self._confirmed = new_confirmed
        self._gc()

        if not rolled_back:
            requests.append(
                SaveRequest(self.current_frame, SaveCell(self, self.current_frame))
            )
        inputs, status = self._inputs_for(self.current_frame)
        requests.append(AdvanceRequest(inputs, status))
        self.current_frame = frame_add(self.current_frame, 1)
        self._stream_confirmed_to_spectators()
        return requests

    def _inputs_for(self, frame: int) -> Tuple[np.ndarray, np.ndarray]:
        inputs = np.zeros((self._num_players, *self.input_shape), self.input_dtype)
        status = np.zeros((self._num_players,), np.int8)
        for h in range(self._num_players):
            if (
                h in self.remote_handle_addr
                and self.endpoints[self.remote_handle_addr[h]].disconnected
            ):
                # frames at or before the disconnect-consensus frame keep
                # their REAL confirmed input (a deep rollback spanning
                # pre-disconnect frames must reproduce the original sim —
                # zeroing them would desync the survivor from its own
                # ring); only frames past it bake the disconnect policy
                v = self.queues[h].confirmed_input(frame)
                if v is not None:
                    inputs[h] = v
                    status[h] = InputStatus.CONFIRMED
                else:
                    status[h] = InputStatus.DISCONNECTED
                continue
            value, st = self.queues[h].input_for(frame)
            inputs[h] = value
            status[h] = st
        return inputs, status

    def _force_disconnect_correction(self, addr) -> None:
        """A remote endpoint just hit the disconnect timeout: frames advanced
        with served predictions for its handles will never be corrected by
        the wire (its packets are dropped from here on).  Adopt OUR last
        real frame as the disconnect-consensus frame for each of its
        handles (forcing the rollback that bakes the disconnect policy in
        BEFORE ``_compute_confirmed`` — which skips disconnected remotes —
        can leapfrog the uncorrected predictions), and announce it so every
        survivor converges on the same frame."""
        for h in self._handle_of_addr.get(addr, []):
            self._adopt_disconnect(h, self.queues[h].last_confirmed)

    def _adopt_disconnect(self, handle: int, frame: int) -> None:
        """Adopt a disconnect-consensus frame for ``handle`` (GGPO-style
        min rule): keep real inputs up to ``frame``, resimulate everything
        after it as DISCONNECTED/zero, and rebroadcast the adopted value.

        The adoption is clamped to our confirmed frame: frames at or below
        it may already be pruned from the snapshot ring, so a notice
        reaching further back than that cannot be honored — the residual
        divergence (the announcer never received an input we already
        finalized) is the classic disconnect race; desync detection is the
        backstop, and the min-rule plus prompt notices make it vanishingly
        rare in practice (survivors stall within one prediction window of
        the dead peer's stream, so their knowledge differs by at most the
        frames in flight)."""
        q = self.queues[handle]
        f = frame_min(frame, q.last_confirmed)
        if self._confirmed != NULL_FRAME and frame_lt(f, self._confirmed):
            f = self._confirmed
        cur = self._disc_frame.get(handle)
        if cur is not None and frame_ge(f, cur):
            return  # min rule: only ever adopt downward
        self._disc_frame[handle] = f
        q.truncate_after(f)
        nxt = frame_add(f, 1)
        if frame_lt(nxt, self.current_frame) and (
            q.first_incorrect == NULL_FRAME
            or frame_lt(nxt, q.first_incorrect)
        ):
            # frames after f were advanced on richer inputs (or stale
            # predictions): the standard mismatch-rollback path replays
            # them under the disconnect policy (a structural truncation,
            # not a served-prediction mismatch — attribution reads the flag)
            q.first_incorrect = nxt
            q.first_incorrect_mismatch = False
        self._disc_notices[handle] = (f, now_s() + DISC_NOTICE_REBROADCAST_S)

    def _make_on_disc_notice(self, addr):
        def cb(handle: int, frame: int) -> None:
            dead_addr = self.remote_handle_addr.get(handle)
            if dead_addr is None or dead_addr == addr:
                return  # our own handle, unknown, or a peer announcing itself
            ep = self.endpoints[dead_addr]
            if not ep.disconnected:
                # consistency over liveness (GGPO): a peer the others
                # dropped is dropped here too, immediately — otherwise we
                # would keep confirming inputs the survivors will never see.
                # UNAUTHENTICATED by design: trusted-peer model, see
                # docs/architecture.md "Trust model (networking)"
                ep.disconnected = True
                ep.events.append(Disconnected(dead_addr))
                self._disc_corrected.add(dead_addr)
                # adopt EVERY handle of the dead peer from local knowledge
                # first: the notice names one handle, but a multi-handle
                # peer's other streams need their correction even if the
                # announcer's per-handle notices never arrive
                self._force_disconnect_correction(dead_addr)
            self._adopt_disconnect(handle, frame)

        return cb

    def _compute_confirmed(self) -> int:
        c = self.current_frame
        for h, addr in self.remote_handle_addr.items():
            if self.endpoints[addr].disconnected:
                continue
            c = frame_min(c, self.queues[h].last_confirmed)
        return c

    def _gc(self) -> None:
        horizon = frame_add(self._confirmed, -self._max_prediction - 2)
        for q in self.queues.values():
            q.gc(horizon)
        acked = _min_ack(self.endpoints.values())
        if acked is None:
            self._local_sent = []  # no connected remotes: nothing to deliver
        elif acked != NULL_FRAME:
            self._local_sent = [
                p for p in self._local_sent if frame_gt(p[0], acked)
            ]
        if len(self._local_sent) > MAX_UNACKED_FRAMES:
            self._local_sent = self._local_sent[-MAX_UNACKED_FRAMES:]
        for fr in [f for f in self._local_checksums if frame_lt(f, horizon)]:
            entry = self._local_checksums.pop(fr)
            if (
                callable(entry)
                and self.desync_detection.enabled
                and fr % self.desync_detection.interval == 0
                and frame_le(fr, self._confirmed)
            ):
                # backstop: an interval frame leaving the window whose async
                # copy never landed — force it now (ONE blocking readback,
                # counted as forced) rather than silently dropping the
                # comparison.  Steady state never reaches this: harvest()
                # lands copies within a tick or two while the horizon trails
                # confirmed by max_prediction + 2 frames.
                v = self._resolve_checksum(entry, True)
                if v is not None:
                    self._publish_checksum(fr, v)
                    self._compare_checksum(fr, v)
        for key in [k for k in self._remote_checksums if frame_lt(k[1], horizon)]:
            del self._remote_checksums[key]

    # -- spectator streaming -------------------------------------------------

    def _stream_confirmed_to_spectators(self) -> None:
        if not self.spectator_endpoints:
            return
        while frame_le(self._next_spectator_frame, self._confirmed):
            f = self._next_spectator_frame
            rows = []
            stats = bytearray()
            for h in range(self._num_players):
                v = self.queues[h].confirmed_input(f)
                if v is None:
                    # stream the status the HOST's sim actually used, so a
                    # status-sensitive spectator replays bit-identically:
                    # a dead player's post-consensus frames are
                    # DISCONNECTED; pre-stream-base frames were advanced
                    # on the PREDICTED default
                    disc = (
                        h in self.remote_handle_addr
                        and self.endpoints[
                            self.remote_handle_addr[h]
                        ].disconnected
                    )
                    stats.append(
                        int(InputStatus.DISCONNECTED)
                        if disc
                        else int(InputStatus.PREDICTED)
                    )
                    v = self.queues[h].default_input()
                else:
                    stats.append(int(InputStatus.CONFIRMED))
                rows.append(np.ascontiguousarray(v).tobytes())
            self._spectator_sent.append((f, b"".join(rows) + bytes(stats)))
            self._next_spectator_frame = frame_add(self._next_spectator_frame, 1)
        acked = _min_ack(self.spectator_endpoints.values())
        if acked is None:
            self._spectator_sent = []  # every spectator disconnected
        elif acked != NULL_FRAME:
            self._spectator_sent = [
                p for p in self._spectator_sent if frame_gt(p[0], acked)
            ]
        if len(self._spectator_sent) > MAX_UNACKED_FRAMES:
            self._spectator_sent = self._spectator_sent[-MAX_UNACKED_FRAMES:]

    # -- desync detection ----------------------------------------------------

    def _on_cell_saved(self, frame: int, provider) -> None:
        if self.desync_detection.enabled:
            self._local_checksums[frame] = provider

    def check_now(self) -> None:
        """Flush point: force every deferred local checksum provider and
        publish/compare immediately (``Runner.finish()`` / ``set_session``
        reach this through the same ``check_now`` hook SyncTest uses).  The
        steady-state path never forces — see :meth:`_drive_desync_detection`."""
        self._drive_desync_detection(force=True)

    @staticmethod
    def _resolve_checksum(provider, force: bool):
        """Provider -> masked 64-bit value, or None when not yet available.

        The non-forcing path uses the provider's ``peek()`` (non-blocking;
        starts the device->host copy and returns None until it lands — the
        driver simply retries next poll, riding the protocol's existing
        tolerance for late checksums).  Forcing blocks on the device and is
        reserved for flush points, the GC backstop, and eager/sync mode
        (allowlisted in the hot-loop purity lint)."""
        if not force:
            peek = getattr(provider, "peek", None)
            if peek is not None:
                v = peek()
            else:
                v = provider()  # host-side provider: no device to wait on
        else:
            v = provider()
        return None if v is None else v & (2**64 - 1)

    def _publish_checksum(self, frame: int, value: int) -> None:
        for ep in self.endpoints.values():
            if not ep.disconnected and ep.state == SessionState.RUNNING:
                ep.send_checksum(frame, value)

    def _compare_checksum(self, frame: int, local: int) -> None:
        """Compare a resolved local checksum against any received reports."""
        for (addr, f), remote in list(self._remote_checksums.items()):
            if f == frame:
                if remote != local:
                    telemetry.count(
                        "checksum_mismatch_total",
                        help="frames whose checksums disagreed", kind="p2p",
                    )
                    self.events_buf.append(
                        DesyncDetected(
                            frame=f,
                            local_checksum=local,
                            remote_checksum=remote,
                            addr=addr,
                        )
                    )
                del self._remote_checksums[(addr, f)]

    def _drive_desync_detection(self, force: bool = False) -> None:
        if not self.desync_detection.enabled:
            return
        interval = self.desync_detection.interval
        remote_frames = {f for (_, f) in self._remote_checksums}
        for frame in sorted(self._local_checksums):
            if frame % interval != 0 or not frame_le(frame, self._confirmed):
                continue
            entry = self._local_checksums[frame]
            if callable(entry):
                entry = self._resolve_checksum(
                    entry, force or self.eager_checksums
                )
                if entry is None:
                    continue  # copy in flight — retry next poll
                self._local_checksums[frame] = entry
                self._publish_checksum(frame, entry)
            # a resolved local sticks around until the remote report shows
            # up (or GC) — only walk the comparison dict when it has a
            # matching frame, not on every poll
            if frame in remote_frames:
                self._compare_checksum(frame, entry)
