"""Input-stream recording and deterministic replay.

A rollback-netcode session is fully determined by its confirmed input
stream, so recording (frame -> all-player inputs) gives free match replays
and a desync post-mortem tool: re-run the recording against any build and
compare checksums frame by frame.  (The reference has no replay facility;
this is a natural extension of its determinism model.)

``InputRecorder`` plugs into :class:`~bevy_ggrs_tpu.runner.GgrsRunner` via
the ``on_advance`` + ``on_confirmed`` hooks.  Every advance is recorded and
a rollback's corrective re-advance overwrites the mispredicted one; a frame
becomes *final* once the session's confirmed frame passes it (a correctly-
predicted frame is never re-advanced, so waiting for an all-confirmed
advance would leave permanent gaps in P2P recordings) or when its advance
already carried all-CONFIRMED inputs.  ``ReplaySession`` feeds the final
frames back through the normal driver as an advance-only session."""

from __future__ import annotations

from typing import Dict, List, Optional, Set

import numpy as np

from ..utils.frames import NULL_FRAME, frame_add, frame_le
from .events import InputStatus, PredictionThresholdError
from .requests import AdvanceRequest


class InputRecorder:
    """Captures the confirmed input stream via the runner's on_advance/on_confirmed hooks."""
    def __init__(self, num_players: int, input_shape=(), input_dtype=np.uint8,
                 canonical_depth=None, canonical_branches=None):
        self.num_players = num_players
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        # program config: replays of variant-unstable float sims are only
        # bit-faithful under the same canonical program (docs/determinism.md)
        self.canonical_depth = canonical_depth
        self.canonical_branches = canonical_branches
        self.frames: Dict[int, np.ndarray] = {}
        # per-frame statuses the sim ACTUALLY used (a dead player's
        # post-consensus frames are DISCONNECTED; replays of
        # status-sensitive models must reproduce that, not all-CONFIRMED)
        self.statuses: Dict[int, np.ndarray] = {}
        self._all_confirmed: Set[int] = set()
        self._watermark: int = NULL_FRAME  # session confirmed frame

    @classmethod
    def for_app(cls, app) -> "InputRecorder":
        """Recorder matching the app's input spec and canonical config."""
        return cls(app.num_players, app.input_shape, app.input_dtype,
                   app.canonical_depth, app.canonical_branches)

    def on_advance(self, frame: int, inputs: np.ndarray, status: np.ndarray) -> None:
        """Runner hook: called for every executed AdvanceFrame request.

        Records unconditionally — a later corrective re-advance (rollback)
        overwrites, so by the time a frame is final the stored value is the
        confirmed truth."""
        self.frames[frame] = np.array(inputs, self.input_dtype)
        self.statuses[frame] = np.array(status, np.int8)
        if np.all(status == InputStatus.CONFIRMED):
            self._all_confirmed.add(frame)

    def on_confirmed(self, frame: int) -> None:
        """Runner hook: the session's confirmed frame advanced to ``frame``."""
        if self._watermark == NULL_FRAME or frame_le(self._watermark, frame):
            self._watermark = frame

    def _is_final(self, frame: int) -> bool:
        # recorded key = post-advance frame; its transition consumed the
        # inputs AT key-1, which are final once confirmed >= key-1, i.e.
        # key <= confirmed+1.  Rollbacks only ever target frames beyond the
        # confirmed frame, so these keys can never be re-advanced again.
        if frame in self._all_confirmed:
            return True
        return self._watermark != NULL_FRAME and frame_le(
            frame, frame_add(self._watermark, 1)
        )

    def final_frames(self) -> Dict[int, np.ndarray]:
        """The confirmed (replay-safe) portion of the recording."""
        return {f: v for f, v in self.frames.items() if self._is_final(f)}

    def __len__(self) -> int:
        return len(self.final_frames())

    # -- persistence --------------------------------------------------------

    def save(self, path: str) -> None:
        """Write the final (confirmed) frames to a compressed .npz file."""
        final = self.final_frames()
        keys = sorted(final)
        np.savez_compressed(
            path,
            frames=np.array(keys, np.int64),
            inputs=np.stack([final[k] for k in keys])
            if keys
            else np.zeros((0, self.num_players, *self.input_shape), self.input_dtype),
            statuses=np.stack([
                self.statuses.get(
                    k, np.full((self.num_players,), InputStatus.CONFIRMED,
                               np.int8)
                )
                for k in keys
            ])
            if keys
            else np.zeros((0, self.num_players), np.int8),
            num_players=self.num_players,
            input_shape=np.array(self.input_shape, np.int64),
            input_dtype=str(self.input_dtype),
            canonical_depth=self.canonical_depth or -1,
            canonical_branches=self.canonical_branches or -1,
        )

    @classmethod
    def load(cls, path: str) -> "InputRecorder":
        """Load a recording written by save()."""
        z = np.load(path, allow_pickle=False)
        cd = int(z["canonical_depth"]) if "canonical_depth" in z else -1
        cb = int(z["canonical_branches"]) if "canonical_branches" in z else -1
        rec = cls(
            int(z["num_players"]),
            tuple(int(x) for x in z["input_shape"]),
            np.dtype(str(z["input_dtype"])),
            canonical_depth=None if cd < 0 else cd,
            canonical_branches=None if cb < 0 else cb,
        )
        stats = z["statuses"] if "statuses" in z else None
        for i, (f, row) in enumerate(zip(z["frames"], z["inputs"])):
            rec.frames[int(f)] = row.astype(rec.input_dtype)
            if stats is not None:
                rec.statuses[int(f)] = stats[i].astype(np.int8)
            rec._all_confirmed.add(int(f))  # saved frames are final
        return rec


class ReplaySession:
    """Advance-only session feeding a recording (GGRS session surface)."""

    is_spectator = True  # drives the advance-only runner path

    def __init__(self, recording: InputRecorder, start_frame: Optional[int] = None):
        self.rec = recording
        self._frames = recording.final_frames()
        frames = sorted(self._frames)
        self.current_frame = start_frame if start_frame is not None else (
            frames[0] if frames else 0
        )
        self.end_frame = frames[-1] + 1 if frames else 0

    def num_players(self) -> int:
        return self.rec.num_players

    def max_prediction(self) -> int:
        return 0

    def confirmed_frame(self) -> int:
        return frame_add(self.current_frame, -1)

    def current_state(self):
        """Always RUNNING (no network)."""
        from .events import SessionState

        return SessionState.RUNNING

    @property
    def finished(self) -> bool:
        return self.current_frame >= self.end_frame

    def advance_frame(self) -> List:
        """Emit the next recorded frame as a confirmed Advance request."""
        if self.current_frame not in self._frames:
            raise PredictionThresholdError()  # gap or end of recording
        inputs = self._frames[self.current_frame]
        status = self.rec.statuses.get(
            self.current_frame,
            np.full((self.rec.num_players,), InputStatus.CONFIRMED, np.int8),
        )
        self.current_frame = frame_add(self.current_frame, 1)
        return [AdvanceRequest(inputs, status)]
