"""Per-player input queues: delay, prediction, misprediction detection.

The ggrs-core surface reconstructed in SURVEY §2.3: inputs are delayed by
``input_delay`` frames at add time, remote inputs are predicted by repeating
the last confirmed input (``PredictRepeatLast``, /root/reference/src/lib.rs:59),
and the queue records every prediction it serves so the arrival of the real
input can report the *first incorrect frame* — the rollback target."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..utils.frames import NULL_FRAME, frame_gt, frame_le, frame_lt
from .events import InputStatus


def predict_repeat_last(queue: "InputQueue", frame: int):
    """Default predictor: repeat the nearest earlier confirmed input
    (``PredictRepeatLast``, /root/reference/src/lib.rs:59), default input
    before the first real one."""
    if queue.last_confirmed == NULL_FRAME:
        return queue.default_input()
    if frame_le(frame, queue.last_confirmed):
        return queue._nearest_before(frame)
    return queue._inputs[queue.last_confirmed]


class InputQueue:
    """Per-player input queue: delay, prediction, misprediction tracking (see module docstring)."""
    def __init__(self, input_shape=(), input_dtype=np.uint8, delay: int = 0,
                 predictor=None):
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.delay = int(delay)
        # the Config::InputPredictor analog: fn(queue, frame) -> input value
        self.predictor = predictor or predict_repeat_last
        self._inputs: Dict[int, np.ndarray] = {}  # frame -> effective input
        self.last_confirmed = NULL_FRAME  # newest frame with a real input
        self._predictions: Dict[int, np.ndarray] = {}  # frame -> served guess
        self.first_incorrect = NULL_FRAME
        # True when first_incorrect was set by a served-prediction/actual
        # disagreement; False when a disconnect-consensus truncation set it
        # structurally (session._adopt_disconnect).  Read alongside
        # take_first_incorrect() for rollback-cause attribution.
        self.first_incorrect_mismatch = False
        self._base: int | None = None  # first frame of the stream, if known

    def default_input(self) -> np.ndarray:
        return np.zeros(self.input_shape, self.input_dtype)

    # -- adding real inputs -------------------------------------------------

    def add_local(self, frame: int, value) -> int:
        """Add a local input at ``frame``; lands at ``frame + delay``.
        Returns the effective frame."""
        eff = frame + self.delay
        self._store(eff, np.asarray(value, self.input_dtype).reshape(self.input_shape))
        return eff

    def add_remote(self, frame: int, value) -> None:
        """Add a remote input already carrying its effective frame (the sender
        applied its own delay)."""
        self._store(frame, np.asarray(value, self.input_dtype).reshape(self.input_shape))

    def _store(self, frame: int, value: np.ndarray) -> None:
        if self.last_confirmed != NULL_FRAME and frame_le(frame, self.last_confirmed):
            return  # duplicate / redundancy (contiguity => already stored)
        if frame in self._inputs:
            return
        self._inputs[frame] = value
        # last_confirmed is the CONTIGUOUS high-water mark (anchored at the
        # stream base when known, else the first frame stored); out-of-order
        # arrivals (a lost chunk refilled later) park above it until the gap
        # closes
        if self.last_confirmed == NULL_FRAME:
            if self._base is not None and frame != self._base:
                return self._recheck_contig()  # parked until the base arrives
            self.last_confirmed = frame
        self._recheck_contig()
        served = self._predictions.pop(frame, None)
        if served is not None and not np.array_equal(served, value):
            if self.first_incorrect == NULL_FRAME or frame_lt(
                frame, self.first_incorrect
            ):
                self.first_incorrect = frame
                self.first_incorrect_mismatch = True

    def set_base(self, base: int) -> None:
        """Anchor the contiguity mark at the sender's first-ever frame."""
        self._base = base
        self._recheck_contig()

    def _recheck_contig(self) -> None:
        from ..utils.frames import frame_add

        if self.last_confirmed == NULL_FRAME and self._base is not None \
                and self._base in self._inputs:
            self.last_confirmed = self._base
        while self.last_confirmed != NULL_FRAME and \
                frame_add(self.last_confirmed, 1) in self._inputs:
            self.last_confirmed = frame_add(self.last_confirmed, 1)

    # -- reading ------------------------------------------------------------

    def input_for(self, frame: int) -> Tuple[np.ndarray, InputStatus]:
        """Input to use when advancing ``frame`` -> ``frame+1``.

        Confirmed if a real input exists; otherwise PredictRepeatLast, with
        the served guess recorded for later misprediction detection."""
        if frame in self._inputs:
            return self._inputs[frame], InputStatus.CONFIRMED
        pred = np.asarray(self.predictor(self, frame), self.input_dtype).reshape(
            self.input_shape
        )
        self._predictions[frame] = pred
        return pred, InputStatus.PREDICTED

    def _nearest_before(self, frame: int) -> np.ndarray:
        best, best_f = self.default_input(), None
        for f, v in self._inputs.items():
            if frame_lt(f, frame) and (best_f is None or frame_gt(f, best_f)):
                best, best_f = v, f
        return best

    def confirmed_input(self, frame: int) -> Optional[np.ndarray]:
        return self._inputs.get(frame)

    def take_first_incorrect(self) -> int:
        """Pop the earliest mispredicted frame (NULL_FRAME if none).
        ``first_incorrect_mismatch`` holds this pop's mismatch/structural
        flag until the next first_incorrect is recorded — callers read it
        immediately after popping (rollback-cause attribution)."""
        f = self.first_incorrect
        self.first_incorrect = NULL_FRAME
        return f

    def inputs_since(self, frame: int) -> list[tuple[int, np.ndarray]]:
        """All confirmed inputs with frame > ``frame``, ascending (for
        redundant INPUT packets)."""
        out = [(f, v) for f, v in self._inputs.items() if frame_gt(f, frame)]
        out.sort(key=lambda t: t[0])
        return out

    def truncate_after(self, frame: int) -> None:
        """Discard real inputs newer than ``frame`` and pull the contiguity
        mark back to it — the disconnect-frame consensus adoption: frames
        past the agreed point must resimulate under the disconnect policy
        even if we received more of the stream than other survivors did."""
        for g in [g for g in self._inputs if frame_gt(g, frame)]:
            del self._inputs[g]
        if self.last_confirmed != NULL_FRAME and frame_gt(
            self.last_confirmed, frame
        ):
            self.last_confirmed = (
                frame
                if frame != NULL_FRAME and frame in self._inputs
                else NULL_FRAME
            )
            self._recheck_contig()

    def gc(self, before_frame: int) -> None:
        """Drop inputs/predictions older than ``before_frame``."""
        for d in (self._inputs, self._predictions):
            for f in [f for f in d if frame_lt(f, before_frame)]:
                del d[f]
