"""SyncTestSession — the determinism oracle.

Semantics per SURVEY §2.3/§3.5 (reconstructed from
/root/reference/src/schedule_systems.rs:85-118,199-209 and
tests/common/mod.rs): every ``advance_frame`` the session emits requests that
(1) save and advance the live frame, then (2) roll back ``check_distance``
frames and re-simulate to the present, saving each frame again.  Each frame
thus gets checksummed once live and ~check_distance more times from
progressively older snapshots; any disagreement raises
:class:`MismatchedChecksumError` on the next ``advance_frame`` (the driver
surfaces it as a SyncTestMismatch event).  Confirmed frame =
``current - check_distance`` (schedule_systems.rs:206-209).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from .. import telemetry
from ..snapshot.lazy import readback_queue
from ..utils.frames import NULL_FRAME, frame_add, frame_diff
from .events import InputStatus, InvalidRequestError, MismatchedChecksumError
from .requests import (
    AdvanceRequest,
    LoadRequest,
    RollbackCause,
    SaveCell,
    SaveRequest,
)


class SyncTestSession:
    """Continuous-resimulation determinism oracle (see module docstring)."""
    def __init__(
        self,
        num_players: int,
        input_shape=(),
        input_dtype=np.uint8,
        check_distance: int = 2,
        input_delay: int = 0,
        max_prediction: int = 8,
        initial_frame: int = 0,
        compare_interval: int = None,
    ):
        self._num_players = num_players
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.check_distance = int(check_distance)
        self.input_delay = int(input_delay)
        self._max_prediction = max(max_prediction, check_distance + 1)
        self.current_frame = initial_frame
        self._age = 0  # ticks since session start (rollback warmup gate)
        # Comparison cadence: checksum providers force a device->host pull,
        # which on high-latency device links costs a flat round-trip (see
        # snapshot/lazy.py).  Comparing every `compare_interval` ticks batches
        # many frames' pulls into one transfer; detection is delayed by at
        # most that many ticks (the error still names the exact mismatched
        # frames).  None = auto: prompt (1) on CPU where pulls are memcpys,
        # 32 on accelerator backends.
        self._compare_interval = compare_interval
        self._ticks_since_compare = 0
        self._compares_run = 0  # see __del__ silent-oracle guard
        # frame -> [P, *shape] effective (post-delay) confirmed inputs
        self._inputs: Dict[int, np.ndarray] = {}
        self._staged: Dict[int, np.ndarray] = {}
        # frame -> list of (checksum provider | forced int)
        self._cells: Dict[int, List] = {}
        # frame -> entry count at last comparison (cells stay in _cells
        # after comparing — later resim saves must compare against history —
        # so pending_comparisons needs a watermark to tell compared apart)
        self._compared_len: Dict[int, int] = {}

    # -- GGRS session surface ---------------------------------------------

    def num_players(self) -> int:
        return self._num_players

    def max_prediction(self) -> int:
        return self._max_prediction

    def rollback_window(self) -> int:
        """Deepest rollback this session will ever request: every tick it
        rolls back exactly ``check_distance`` frames
        (schedule_systems.rs:85-118), regardless of ``max_prediction``."""
        return self.check_distance

    def confirmed_frame(self) -> int:
        """current - check_distance once the warmup window has passed."""
        if self.check_distance == 0:
            return self.current_frame
        if self._age < self.check_distance:
            return NULL_FRAME  # session too young to have confirmed anything
        return frame_add(self.current_frame, -self.check_distance)

    def add_local_input(self, handle: int, value) -> None:
        """Stage this tick's input for a handle."""
        if not (0 <= handle < self._num_players):
            raise InvalidRequestError(f"invalid player handle {handle}")
        arr = np.asarray(value, self.input_dtype).reshape(self.input_shape)
        self._staged[handle] = arr

    def advance_frame(self) -> List:
        """Emit save/advance plus the rollback-and-resimulate request batch."""
        if len(self._staged) != self._num_players:
            missing = set(range(self._num_players)) - set(self._staged)
            raise InvalidRequestError(f"missing local input for players {missing}")

        self._ticks_since_compare += 1
        if self._ticks_since_compare >= self.compare_interval():
            self._ticks_since_compare = 0
            self._check_mismatches()

        # apply input delay: input staged now takes effect at frame+delay;
        # frames before the first delayed input see the default (zero) input
        eff_frame = frame_add(self.current_frame, self.input_delay)
        packed = np.stack(
            [self._staged[h] for h in range(self._num_players)]
        ).astype(self.input_dtype)
        self._inputs[eff_frame] = packed
        self._staged.clear()

        f = self.current_frame
        status = np.full((self._num_players,), InputStatus.CONFIRMED, np.int8)
        requests: List = [
            SaveRequest(f, SaveCell(self, f)),
            AdvanceRequest(self._input_for(f), status),
        ]
        d = self.check_distance
        if d > 0 and self._age + 1 >= d:
            t = frame_add(f, 1 - d)
            # structural re-simulation, not a blamed peer: the cause tags
            # the oracle itself so rollback_cause_total sums still cover
            # every rollback without pinning SyncTest churn on a player
            requests.append(LoadRequest(t, cause=RollbackCause(
                handle="resim", frame=t, lateness=d, mismatch=False,
                kind="resim",
            )))
            i = t
            while i != frame_add(f, 1):
                requests.append(AdvanceRequest(self._input_for(i), status))
                requests.append(SaveRequest(frame_add(i, 1), SaveCell(self, frame_add(i, 1))))
                i = frame_add(i, 1)
        self.current_frame = frame_add(f, 1)
        self._age += 1
        self._gc()
        return requests

    def compare_interval(self) -> int:
        """Effective comparison cadence (resolves the auto default)."""
        if self._compare_interval is None:
            try:
                import jax

                self._compare_interval = (
                    1 if jax.default_backend() == "cpu" else 32
                )
            except Exception:
                self._compare_interval = 1
        return self._compare_interval

    def check_now(self) -> None:
        """Force all pending checksum comparisons immediately (raises
        :class:`MismatchedChecksumError` like ``advance_frame`` would).
        Call at session teardown when running with a deferred
        ``compare_interval``."""
        self._ticks_since_compare = 0
        self._check_mismatches()

    def pending_comparisons(self) -> int:
        """Frames with ≥2 saved checksums of which at least one arrived
        after the frame's last comparison (a nonzero value at teardown means
        the oracle has unchecked data — call :meth:`check_now` /
        ``runner.finish()``)."""
        return sum(
            1
            for f, entries in self._cells.items()
            if len(entries) >= 2
            and self._compared_len.get(f, 0) < len(entries)
        )

    def __del__(self):
        # Deferred comparison (compare_interval > 1, the accelerator default)
        # must not let a short run exit with the oracle silently unexercised:
        # a SyncTest that never compared anything proves nothing.
        try:
            if self._compares_run == 0 and self.pending_comparisons() > 0:
                import warnings

                warnings.warn(
                    "SyncTestSession dropped with NO checksum comparisons "
                    f"ever performed ({self.pending_comparisons()} frames "
                    "pending) — the determinism oracle never ran; call "
                    "runner.finish() or session.check_now() before teardown "
                    f"(compare_interval={self._compare_interval})",
                    RuntimeWarning,
                    stacklevel=1,
                )
        except Exception:
            pass  # interpreter teardown: modules may already be gone

    # -- internals ---------------------------------------------------------

    def _input_for(self, frame: int) -> np.ndarray:
        default = np.zeros((self._num_players, *self.input_shape), self.input_dtype)
        return self._inputs.get(frame, default)

    def _on_cell_saved(self, frame: int, provider) -> None:
        self._cells.setdefault(frame, []).append(provider)

    def _check_mismatches(self) -> None:
        # collect any landed async checksum copies first: with the pipelined
        # runner, most providers forced below resolve from the harvested
        # cache instead of blocking on the device
        readback_queue().harvest()
        mismatched = []
        for frame, entries in self._cells.items():
            if len(entries) < 2:
                continue
            # only a frame with >=2 checksums is a real comparison — a
            # vacuous sweep must not satisfy the __del__ silent-oracle guard
            self._compares_run += 1
            self._compared_len[frame] = len(entries)
            vals = set()
            for i, e in enumerate(entries):
                v = e() if callable(e) else e
                entries[i] = v  # memoize forced value
                if v is not None:
                    vals.add(v)
            if len(vals) > 1:
                mismatched.append(frame)
        if mismatched:
            import os
            if os.environ.get("BGT_DEBUG_MISMATCH"):
                for fr in mismatched:
                    print(f"MISMATCH frame {fr}: "
                          f"{[hex(v) if isinstance(v, int) else v for v in self._cells[fr]]}",
                          flush=True)
            frames = sorted(mismatched)
            telemetry.count(
                "checksum_mismatch_total", len(frames),
                help="frames whose checksums disagreed", kind="synctest",
            )
            for fr in frames:
                del self._cells[fr]
                self._compared_len.pop(fr, None)
            raise MismatchedChecksumError(self.current_frame, frames)

    def _gc(self) -> None:
        # a frame can still receive saves until current passes it by d+1;
        # cells additionally survive the deferred-comparison window so no
        # frame is ever dropped uncompared
        cell_horizon = frame_add(
            self.current_frame,
            -self.check_distance - 2 - self.compare_interval(),
        )
        for fr in [fr for fr in self._cells if frame_diff(fr, cell_horizon) < 0]:
            del self._cells[fr]
            self._compared_len.pop(fr, None)
        horizon = frame_add(self.current_frame, -self.check_distance - 2)
        for fr in [fr for fr in self._inputs if frame_diff(fr, horizon) < 0]:
            del self._inputs[fr]
