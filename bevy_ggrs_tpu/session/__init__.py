from .events import (
    InputStatus,
    SessionState,
    PlayerType,
    Player,
    DesyncDetection,
    Synchronizing,
    Synchronized,
    Disconnected,
    NetworkInterrupted,
    NetworkResumed,
    DesyncDetected,
    GgrsError,
    PredictionThresholdError,
    MismatchedChecksumError,
    NotSynchronizedError,
    InvalidRequestError,
    NetworkStats,
)
from .requests import SaveRequest, LoadRequest, AdvanceRequest, SaveCell, GgrsRequest
from .synctest import SyncTestSession
from .input_queue import InputQueue
from .time_sync import TimeSync
from .transport import TcpNonBlockingSocket, UdpNonBlockingSocket, NonBlockingSocket
from .p2p import P2PSession
from .spectator import SpectatorSession
from .builder import SessionBuilder
from .native import NativeP2PSession, native_available
from .room import RoomServer, RoomSocket, assign_handles, wait_for_players
from .replay import InputRecorder, ReplaySession

__all__ = [
    "InputStatus",
    "SessionState",
    "PlayerType",
    "Player",
    "DesyncDetection",
    "Synchronizing",
    "Synchronized",
    "Disconnected",
    "NetworkInterrupted",
    "NetworkResumed",
    "DesyncDetected",
    "GgrsError",
    "PredictionThresholdError",
    "MismatchedChecksumError",
    "NotSynchronizedError",
    "InvalidRequestError",
    "NetworkStats",
    "SaveRequest",
    "LoadRequest",
    "AdvanceRequest",
    "SaveCell",
    "GgrsRequest",
    "SyncTestSession",
    "InputQueue",
    "TimeSync",
    "UdpNonBlockingSocket",
    "TcpNonBlockingSocket",
    "NonBlockingSocket",
    "P2PSession",
    "SpectatorSession",
    "SessionBuilder",
    "NativeP2PSession",
    "native_available",
    "RoomServer",
    "RoomSocket",
    "assign_handles",
    "wait_for_players",
    "InputRecorder",
    "ReplaySession",
]
