"""Frame-advantage time synchronisation.

Drives the run-slow flow control: each peer tracks how many frames it is
ahead of each remote (local advantage) and learns the remote's view from
quality reports; ``frames_ahead`` is the smoothed half-difference.  The
driver slows the frame period by x11/10 while positive
(/root/reference/src/schedule_systems.rs:34-38,65)."""

from __future__ import annotations

from collections import deque
from typing import Deque

WINDOW = 40  # frames of smoothing


class TimeSync:
    """Rolling-window frame-advantage smoothing (drives run-slow).

    Warm-up semantics: before the first quality report lands, the remote
    window is empty.  The old behavior returned 0 from :meth:`frames_ahead`
    until BOTH windows had data — hiding real early-session skew behind a
    value indistinguishable from "perfectly synced".  Now the remote mean
    is seeded at 0 (the first ``note_remote`` replaces the seed), so a
    locally-observed advantage shows through immediately, and
    :meth:`warmed_up` lets dashboards (the ``time_sync_warmup`` gauge in
    :mod:`bevy_ggrs_tpu.telemetry.netstats`) tell "synced" from "no data
    yet".  Run-slow consumers (``P2PSession.frames_ahead``) gate on
    :meth:`warmed_up` so the scheduler never chases the seed."""
    def __init__(self):
        self.local_adv: Deque[int] = deque(maxlen=WINDOW)
        self.remote_adv: Deque[int] = deque(maxlen=WINDOW)

    def note_local(self, local_frame: int, remote_last_frame: int) -> None:
        self.local_adv.append(local_frame - remote_last_frame)

    def note_remote(self, remote_advantage: int) -> None:
        self.remote_adv.append(remote_advantage)

    def warmed_up(self) -> bool:
        """True once both windows hold at least one real observation —
        i.e. :meth:`frames_ahead` reflects two-sided data, not the zero
        seed standing in for the remote's view."""
        return bool(self.local_adv) and bool(self.remote_adv)

    def local_advantage(self) -> int:
        """Smoothed local frames-ahead of the peer."""
        if not self.local_adv:
            return 0
        return round(sum(self.local_adv) / len(self.local_adv))

    def frames_ahead(self) -> int:
        """Half the smoothed advantage difference: frames we should yield.

        An empty remote window contributes a 0-advantage seed instead of
        suppressing the estimate entirely (see class docstring)."""
        if not self.local_adv:
            return 0
        l = sum(self.local_adv) / len(self.local_adv)
        r = (
            sum(self.remote_adv) / len(self.remote_adv)
            if self.remote_adv
            else 0.0
        )
        return round((l - r) / 2)
