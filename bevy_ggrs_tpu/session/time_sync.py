"""Frame-advantage time synchronisation.

Drives the run-slow flow control: each peer tracks how many frames it is
ahead of each remote (local advantage) and learns the remote's view from
quality reports; ``frames_ahead`` is the smoothed half-difference.  The
driver slows the frame period by x11/10 while positive
(/root/reference/src/schedule_systems.rs:34-38,65)."""

from __future__ import annotations

from collections import deque
from typing import Deque

WINDOW = 40  # frames of smoothing


class TimeSync:
    """Rolling-window frame-advantage smoothing (drives run-slow)."""
    def __init__(self):
        self.local_adv: Deque[int] = deque(maxlen=WINDOW)
        self.remote_adv: Deque[int] = deque(maxlen=WINDOW)

    def note_local(self, local_frame: int, remote_last_frame: int) -> None:
        self.local_adv.append(local_frame - remote_last_frame)

    def note_remote(self, remote_advantage: int) -> None:
        self.remote_adv.append(remote_advantage)

    def local_advantage(self) -> int:
        """Smoothed local frames-ahead of the peer."""
        if not self.local_adv:
            return 0
        return round(sum(self.local_adv) / len(self.local_adv))

    def frames_ahead(self) -> int:
        """Half the smoothed advantage difference: frames we should yield."""
        if not self.local_adv or not self.remote_adv:
            return 0
        l = sum(self.local_adv) / len(self.local_adv)
        r = sum(self.remote_adv) / len(self.remote_adv)
        return round((l - r) / 2)
