"""Room-based matchmaking transport — the matchbox/WebRTC analog.

The reference pairs with `matchbox` for browser P2P
(/root/reference/README.md:79): peers join a ROOM on a signaling server,
learn each other's PeerIds, then exchange unreliable datagrams addressed
BY PEER ID over data channels.  This module provides the same developer
contract over UDP, in the framework's non-blocking polling style:

- :class:`RoomServer` — the signaling/relay node.  Tracks room rosters,
  pushes roster updates to every member on change, prunes silent members,
  and forwards relayed datagrams (the TURN-style data plane, so two peers
  that cannot reach each other directly still play).
- :class:`RoomSocket` — a :class:`~.transport.NonBlockingSocket` whose
  ``addr`` IS the peer id (a string), drop-in for
  ``SessionBuilder.add_player(PlayerType.REMOTE, handle, peer_id)``.
  ``mode="direct"`` sends game datagrams straight to the roster address
  (STUN-style, LAN/loopback); ``mode="relay"`` bounces them through the
  server (works anywhere the server is reachable).
- :func:`assign_handles` — the matchbox convention: sort peer ids, index
  = player handle, so every peer derives the same handle assignment with
  no extra coordination.

Wire format: own magic (0x52A7) so room traffic can never be confused
with session packets; length-prefixed UTF-8 ids; payloads are opaque.
Untrusted input: every decoder bails on malformed bytes (same posture as
session/protocol.py).
"""

from __future__ import annotations

import socket as _socket
import struct
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

ROOM_MAGIC = 0x52A7
_HDR = struct.Struct("<HB")
# message types
_JOIN = 1      # c->s: room, peer_id
_ROSTER = 2    # s->c: room, [(peer_id, ip, port)...]
_DATA = 3      # c->c (direct): src_peer_id + payload
_RELAY = 4     # c->s: dst_peer_id + payload
_FWD = 5       # s->c: src_peer_id + payload
_PING = 6      # c->s keepalive (also re-requests the roster)
_LEAVE = 7     # c->s: explicit departure
_REJECT = 8    # s->c: room, reason (join refused — e.g. bad join token)

PING_INTERVAL_S = 0.5
MEMBER_TIMEOUT_S = 5.0
# hard cap per room: bounds roster-packet size (the member count is one
# byte on the wire) and stops a single socket from growing a room without
# limit by joining under many peer ids
MAX_ROOM_MEMBERS = 64
# a client that has not seen a roster for this long re-JOINs instead of
# pinging: pings from pruned members are ignored (the server no longer
# knows the addr), so re-registration is the self-heal path — it also
# survives a server restart
REJOIN_AFTER_S = 1.5


def _pack_str(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 255:
        raise ValueError("room/peer id longer than 255 bytes")
    return bytes([len(b)]) + b


class _Reader:
    __slots__ = ("b", "i", "ok")

    def __init__(self, b: bytes):
        self.b = b
        self.i = 0
        self.ok = True

    def take(self, n: int) -> bytes:
        if self.i + n > len(self.b):
            self.ok = False
            return b""
        out = self.b[self.i:self.i + n]
        self.i += n
        return out

    def u8(self) -> int:
        d = self.take(1)
        return d[0] if self.ok else 0

    def u16(self) -> int:
        d = self.take(2)
        return struct.unpack("<H", d)[0] if self.ok else 0

    def s(self) -> str:
        n = self.u8()
        d = self.take(n)
        if not self.ok:
            return ""
        try:
            return d.decode("utf-8")
        except UnicodeDecodeError:
            self.ok = False
            return ""

    def rest(self) -> bytes:
        out = self.b[self.i:]
        self.i = len(self.b)
        return out


class RoomServer:
    """Signaling + relay server.  Drive with :meth:`poll` (non-blocking) —
    from a game loop, a thread, or the ``scripts/room_server.py`` CLI."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 member_timeout_s: float = MEMBER_TIMEOUT_S,
                 join_token: Optional[str] = None):
        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind((host, port))
        self.member_timeout_s = member_timeout_s
        # optional shared-secret admission control (off by default): when
        # set, a JOIN must carry the same token or it is rejected with a
        # reason.  This closes the "any addr can join/kick/impersonate a
        # peer id" hole for deployments that can distribute a secret; it
        # is NOT transport encryption — see docs/architecture.md
        # "Trust model (networking)".
        self.join_token = join_token
        # room -> peer_id -> (addr, last_seen)
        self.rooms: Dict[str, Dict[str, Tuple[Any, float]]] = {}
        self._addr_index: Dict[Any, Tuple[str, str]] = {}  # addr -> (room, peer)

    @property
    def local_addr(self) -> Tuple[str, int]:
        return self._sock.getsockname()

    def poll(self) -> None:
        """Drain the socket; answer joins/pings, forward relays, prune."""
        while True:
            try:
                data, addr = self._sock.recvfrom(65536)
            except (BlockingIOError, OSError):
                break
            self._handle(data, addr)
        self._prune()

    def _handle(self, data: bytes, addr) -> None:
        if len(data) < _HDR.size:
            return
        magic, t = _HDR.unpack_from(data)
        if magic != ROOM_MAGIC:
            return
        r = _Reader(data[_HDR.size:])
        now = time.monotonic()
        if t == _JOIN:
            # membership is claimed, not authenticated unless a join token
            # is configured (trusted-network model — docs/architecture.md
            # "Trust model (networking)")
            room, peer = r.s(), r.s()
            if not r.ok or not room or not peer:
                return
            # optional trailing token field: absent in pre-token clients
            # (old servers likewise ignore the trailing bytes, so a
            # token-carrying client stays compatible with them)
            token = r.s() if r.i < len(r.b) else ""
            if self.join_token is not None and token != self.join_token:
                out = (_HDR.pack(ROOM_MAGIC, _REJECT) + _pack_str(room)
                       + _pack_str("bad join token"))
                self._send(out, addr)
                return
            # destination capacity FIRST: a rejected move must leave the
            # old membership intact (dropping it before the check would
            # deregister the socket entirely on a full destination)
            members = self.rooms.setdefault(room, {})
            prev = self._addr_index.get(addr)
            occupied = len(members)
            if (
                prev is not None
                and prev[0] == room
                and prev[1] in members
                and members[prev[1]][0] == addr
            ):
                # the joining socket already holds a slot HERE — a rejoin
                # under a new peer id frees it, so it must not count against
                # capacity (a full room would otherwise reject its own member)
                occupied -= 1
            if peer not in members and occupied >= MAX_ROOM_MEMBERS:
                return  # room full: drop the join (bounds the roster byte)
            # one socket = one membership: a JOIN from an addr already
            # registered elsewhere moves it (otherwise _prune on the stale
            # membership would pop the LIVE _addr_index entry and the
            # member's pings/relays would be silently ignored)
            if prev is not None and prev != (room, peer):
                self._drop_member(*prev, broadcast=True)
                members = self.rooms.setdefault(room, {})
            old = members.get(peer)
            if old is not None and old[0] != addr:
                # same peer id re-joining from a new port: retire the old
                # addr's index entry so a datagram from the recycled addr
                # can never flip the roster back to a dead socket
                self._addr_index.pop(old[0], None)
            members[peer] = (addr, now)
            self._addr_index[addr] = (room, peer)
            self._broadcast_roster(room)
        elif t == _PING:
            entry = self._addr_index.get(addr)
            if entry is None:
                return
            room, peer = entry
            members = self.rooms.get(room)
            if members is not None and peer in members:
                members[peer] = (addr, now)
                self._send_roster(room, addr)
        elif t == _RELAY:
            entry = self._addr_index.get(addr)
            if entry is None:
                return  # relays only for joined members
            room, src_peer = entry
            dst = r.s()
            payload = r.rest()
            if not r.ok:
                return
            members = self.rooms.get(room, {})
            got = members.get(dst)
            if got is None:
                return  # unknown / departed peer: drop (UDP semantics)
            members[src_peer] = (addr, now)  # relaying proves liveness
            out = _HDR.pack(ROOM_MAGIC, _FWD) + _pack_str(src_peer) + payload
            self._send(out, got[0])
        elif t == _LEAVE:
            entry = self._addr_index.get(addr)
            if entry is None:
                return
            self._drop_member(*entry, broadcast=True)

    def _drop_member(self, room: str, peer: str, broadcast: bool) -> None:
        members = self.rooms.get(room)
        if members is None:
            return
        got = members.pop(peer, None)
        if got is None:
            return
        self._addr_index.pop(got[0], None)
        if not members:
            del self.rooms[room]
        elif broadcast:
            self._broadcast_roster(room)

    def _prune(self) -> None:
        now = time.monotonic()
        for room in list(self.rooms):
            members = self.rooms[room]
            dead = [
                p for p, (addr, seen) in members.items()
                if now - seen > self.member_timeout_s
            ]
            for p in dead:
                self._drop_member(room, p, broadcast=False)
            if dead and room in self.rooms:
                self._broadcast_roster(room)

    def _roster_packet(self, room: str) -> bytes:
        members = self.rooms.get(room, {})
        out = _HDR.pack(ROOM_MAGIC, _ROSTER) + _pack_str(room)
        out += bytes([len(members)])
        for peer, (addr, _) in sorted(members.items()):
            ip, port = addr
            out += _pack_str(peer) + _pack_str(ip) + struct.pack("<H", port)
        return out

    def _broadcast_roster(self, room: str) -> None:
        pkt = self._roster_packet(room)
        for peer, (addr, _) in self.rooms.get(room, {}).items():
            self._send(pkt, addr)

    def _send_roster(self, room: str, addr) -> None:
        self._send(self._roster_packet(room), addr)

    def _send(self, data: bytes, addr) -> None:
        try:
            self._sock.sendto(data, addr)
        except (BlockingIOError, OSError):
            pass

    def close(self) -> None:
        self._sock.close()


class RoomSocket:
    """Peer-id-addressed NonBlockingSocket over a :class:`RoomServer`.

    ``send_to(data, peer_id)`` / ``receive_all() -> [(peer_id, bytes)]`` —
    exactly the session transport protocol, with peer ids as addresses
    (the matchbox contract).  Construct, then drive :meth:`poll_roster`
    (or just call :func:`wait_for_players`) until the room is full, then
    hand to ``SessionBuilder``."""

    def __init__(self, server_addr: Tuple[str, int], room: str,
                 peer_id: Optional[str] = None, mode: str = "direct",
                 port: int = 0, host: str = "0.0.0.0",
                 join_token: Optional[str] = None):
        if mode not in ("direct", "relay"):
            raise ValueError("mode must be 'direct' or 'relay'")
        # resolve once: inbound packets are validated against the source
        # address recvfrom() reports, which is always a numeric IP — a
        # hostname here would never match and all rosters would be dropped
        sip, sport = server_addr
        self.server_addr = (_socket.gethostbyname(sip), int(sport))
        self.room = room
        self.peer_id = peer_id or uuid.uuid4().hex[:12]
        self.mode = mode
        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind((host, port))
        self.roster: Dict[str, Tuple[str, int]] = {}  # peer_id -> addr
        self.join_token = join_token
        self.last_reject: Optional[str] = None  # server's refusal reason
        self._last_ping = 0.0
        self._last_roster = time.monotonic()
        self._join()

    @property
    def local_addr(self) -> Tuple[str, int]:
        return self._sock.getsockname()

    def _join(self) -> None:
        pkt = (_HDR.pack(ROOM_MAGIC, _JOIN)
               + _pack_str(self.room) + _pack_str(self.peer_id))
        if self.join_token is not None:
            # trailing field: old servers ignore it (backward compatible)
            pkt += _pack_str(self.join_token)
        self._raw_send(pkt, self.server_addr)

    def players(self) -> List[str]:
        """Connected peer ids (self included), sorted — the matchbox
        ``players()`` analog; index in this list = player handle
        (see :func:`assign_handles`)."""
        ids = set(self.roster) | {self.peer_id}
        return sorted(ids)

    # -- NonBlockingSocket protocol -----------------------------------------

    def send_to(self, data: bytes, addr: Any) -> None:
        """Send a game datagram to a PEER ID."""
        peer = str(addr)
        if self.mode == "relay":
            pkt = _HDR.pack(ROOM_MAGIC, _RELAY) + _pack_str(peer) + data
            self._raw_send(pkt, self.server_addr)
            return
        got = self.roster.get(peer)
        if got is None:
            return  # not in the roster (yet): drop, UDP semantics
        pkt = _HDR.pack(ROOM_MAGIC, _DATA) + _pack_str(self.peer_id) + data
        self._raw_send(pkt, got)

    def receive_all(self) -> List[Tuple[Any, bytes]]:
        """Drain: game datagrams as ``(peer_id, payload)``; roster/control
        packets are consumed internally.  Also drives the keepalive."""
        out: List[Tuple[Any, bytes]] = []
        while True:
            try:
                data, addr = self._sock.recvfrom(65536)
            except (BlockingIOError, OSError):
                break
            got = self._handle(data, addr)
            if got is not None:
                out.append(got)
        now = time.monotonic()
        if now - self._last_ping >= PING_INTERVAL_S:
            self._last_ping = now
            if now - self._last_roster > REJOIN_AFTER_S:
                self._join()  # pruned or server restarted: re-register
            else:
                self._raw_send(_HDR.pack(ROOM_MAGIC, _PING), self.server_addr)
        return out

    # -- internals -----------------------------------------------------------

    def _handle(self, data: bytes, addr) -> Optional[Tuple[str, bytes]]:
        if len(data) < _HDR.size:
            return None
        magic, t = _HDR.unpack_from(data)
        if magic != ROOM_MAGIC:
            return None
        r = _Reader(data[_HDR.size:])
        if t == _ROSTER:
            if addr != self.server_addr:
                return None  # rosters are authoritative: server-origin only
            room = r.s()
            n = r.u8()
            if not r.ok or room != self.room:
                return None
            roster: Dict[str, Tuple[str, int]] = {}
            for _ in range(n):
                peer, ip, port = r.s(), r.s(), r.u16()
                if not r.ok:
                    return None
                if peer != self.peer_id:
                    roster[peer] = (ip, port)
            self.roster = roster
            self._last_roster = time.monotonic()
            return None
        if t == _REJECT:
            if addr != self.server_addr:
                return None  # rejections are authoritative: server-origin only
            room, reason = r.s(), r.s()
            if r.ok and room == self.room:
                self.last_reject = reason or "join rejected"
            return None
        if t == _FWD:
            if addr != self.server_addr:
                return None  # relayed data comes only from the server
            src = r.s()
            payload = r.rest()
            if not r.ok or not src:
                return None
            return (src, payload)
        if t == _DATA:
            src = r.s()
            payload = r.rest()
            if not r.ok or not src:
                return None
            if self.roster.get(src) != addr:
                return None  # direct data must come from the roster addr
            return (src, payload)
        return None

    def poll_roster(self) -> List[str]:
        """Drive control traffic only (pre-session); returns players()."""
        self.receive_all()
        return self.players()

    def leave(self) -> None:
        self._raw_send(_HDR.pack(ROOM_MAGIC, _LEAVE), self.server_addr)

    def _raw_send(self, data: bytes, addr) -> None:
        try:
            self._sock.sendto(data, addr)
        except (BlockingIOError, OSError):
            pass

    def close(self) -> None:
        """LEAVE the room (so the roster updates promptly) and close."""
        self.leave()
        self._sock.close()


def wait_for_players(sock: RoomSocket, n: int, timeout_s: float = 10.0,
                     server: Optional[RoomServer] = None) -> List[str]:
    """Poll until the room holds ``n`` players (self included) or raise.
    Pass ``server`` to co-drive an in-process RoomServer (tests)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if server is not None:
            server.poll()
        players = sock.poll_roster()
        if sock.last_reject is not None:
            # the server refused the join (e.g. bad join token): fail fast
            # with the reason instead of spinning until the timeout
            raise PermissionError(
                f"room '{sock.room}' join rejected: {sock.last_reject}"
            )
        if len(players) >= n:
            return players
        time.sleep(0.005)
    raise TimeoutError(
        f"room '{sock.room}' has {len(sock.players())}/{n} players"
    )


def assign_handles(sock: RoomSocket) -> Dict[int, str]:
    """Deterministic handle assignment every peer derives identically:
    sorted peer ids, index = handle (the matchbox-tutorial convention)."""
    return {h: p for h, p in enumerate(sock.players())}
