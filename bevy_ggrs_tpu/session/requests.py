"""GgrsRequest stream — the contract between sessions and the driver.

Mirrors ``GgrsRequest::{SaveGameState, LoadGameState, AdvanceFrame}``
(/root/reference/src/schedule_systems.rs:222-269).  Like the reference, the
save cell carries only the *checksum* — real state lives in the driver's
snapshot ring, not in the session (schedule_systems.rs:236: the plugin calls
``cell.save(frame, None, checksum)``).  The checksum is passed as a lazy
provider so a device->host sync only happens when the protocol actually needs
the value (SyncTest comparison, desync-detection interval frames).  Drivers
pass a :class:`~bevy_ggrs_tpu.snapshot.lazy.ChecksumRef` directly: it is
callable (forcing) and additionally offers a non-blocking ``peek()`` that the
pipelined consume paths poll until the async device->host copy lands."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Union

import numpy as np


class SaveCell:
    """Session-owned storage for one saved frame's checksum."""

    def __init__(self, session, frame: int):
        self._session = session
        self.frame = frame

    def save(self, frame: int, checksum_provider: Optional[Callable[[], int]]):
        """Record the checksum provider for this frame (state stays
        driver-side).  The provider is any callable returning the 64-bit
        value (or None); providers with a ``peek()`` method are consumed
        non-blocking by the pipelined sessions."""
        self._session._on_cell_saved(frame, checksum_provider)


@dataclass
class SaveRequest:
    """SaveGameState: snapshot the current frame (cell takes the checksum)."""
    frame: int
    cell: SaveCell


@dataclass
class LoadRequest:
    """LoadGameState: restore the ring snapshot for `frame`."""
    frame: int


@dataclass
class AdvanceRequest:
    """Inputs for one frame: [num_players, ...] array + per-player status."""

    inputs: np.ndarray
    status: np.ndarray  # int8[num_players] of InputStatus values


GgrsRequest = Union[SaveRequest, LoadRequest, AdvanceRequest]
