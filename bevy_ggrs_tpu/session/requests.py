"""GgrsRequest stream — the contract between sessions and the driver.

Mirrors ``GgrsRequest::{SaveGameState, LoadGameState, AdvanceFrame}``
(/root/reference/src/schedule_systems.rs:222-269).  Like the reference, the
save cell carries only the *checksum* — real state lives in the driver's
snapshot ring, not in the session (schedule_systems.rs:236: the plugin calls
``cell.save(frame, None, checksum)``).  The checksum is passed as a lazy
provider so a device->host sync only happens when the protocol actually needs
the value (SyncTest comparison, desync-detection interval frames).  Drivers
pass a :class:`~bevy_ggrs_tpu.snapshot.lazy.ChecksumRef` directly: it is
callable (forcing) and additionally offers a non-blocking ``peek()`` that the
pipelined consume paths poll until the async device->host copy lands."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Union

import numpy as np


class SaveCell:
    """Session-owned storage for one saved frame's checksum."""

    def __init__(self, session, frame: int):
        self._session = session
        self.frame = frame

    def save(self, frame: int, checksum_provider: Optional[Callable[[], int]]):
        """Record the checksum provider for this frame (state stays
        driver-side).  The provider is any callable returning the 64-bit
        value (or None); providers with a ``peek()`` method are consumed
        non-blocking by the pipelined sessions."""
        self._session._on_cell_saved(frame, checksum_provider)


@dataclass
class SaveRequest:
    """SaveGameState: snapshot the current frame (cell takes the checksum)."""
    frame: int
    cell: SaveCell


@dataclass
class RollbackCause:
    """Why a LoadRequest happened — the rollback-cause attribution payload.

    ``handle`` is the blamed player handle (the queue whose earliest
    mispredicted frame won the rollback-target minimum), or a string tag
    for structural rollbacks: ``"resim"`` for SyncTest's per-tick
    re-simulation, ``"unknown"`` when the core could not attribute (the
    native decode path with multiple remote handles).  ``lateness`` is how
    many frames behind the session's current frame the correcting input
    arrived — the depth the blamed peer cost us.  ``mismatch`` is True when
    the cause was a served-prediction/actual-input disagreement (as opposed
    to a disconnect-consensus truncation or a structural resim)."""

    handle: object = "unknown"
    frame: int = 0
    lateness: int = 0
    mismatch: bool = False
    kind: str = "misprediction"  # | "disconnect" | "resim" | "unknown"


@dataclass
class LoadRequest:
    """LoadGameState: restore the ring snapshot for `frame`.

    ``cause`` carries the rollback-cause attribution when the session can
    name it (None from legacy/replay paths; the driver then attributes the
    rollback to handle ``"unknown"`` so ``rollback_cause_total`` summed over
    handles always equals ``rollbacks_total``)."""
    frame: int
    cause: Optional[RollbackCause] = None


@dataclass
class AdvanceRequest:
    """Inputs for one frame: [num_players, ...] array + per-player status."""

    inputs: np.ndarray
    status: np.ndarray  # int8[num_players] of InputStatus values


GgrsRequest = Union[SaveRequest, LoadRequest, AdvanceRequest]
