"""Wire protocol + per-peer endpoint state machine.

The network core hidden behind ``poll_remote_clients``/``advance_frame`` in
the reference's ggrs dependency (SURVEY §5.8): non-blocking UDP, poll-driven,
with sync handshake, redundant input packets, input acks, quality
reports (ping + frame advantage), keepalives, disconnect detection, and
desync-detection checksum reports.

The byte format is little-endian and fixed (shared with the native C++ core
in native/ggrs_core — keep in sync with message.h):

    header:  magic:u16  type:u8
    SYNC_REQ   nonce:u32 version:u8
    SYNC_REP   nonce:u32 version:u8
               (version gates the handshake: mismatched or missing version
               gets no reply, so mixed-version pairs stall in SYNCHRONIZING
               instead of mis-parsing each other's streams)
    INPUT      start_frame:i32 count:u16 ack_frame:i32 advantage:i8
               stream_base:i32 payload: count * input_size bytes
               (stream_base = sender's first-ever input frame: lets a
               receiver anchor its contiguous-ack mark even if the earliest
               packets were lost)
    INPUT_ACK  ack_frame:i32
    QUAL_REQ   ping_ts_us:u64 advantage:i8
    QUAL_REP   pong_ts_us:u64
    KEEP_ALIVE (empty)
    CHECKSUM   frame:i32 checksum:u64
    DISC_NOTICE handle:i16 frame:i32  (disconnect-frame consensus,
               implemented by BOTH cores; peers lacking the message type
               ignore it and keep local-knowledge disconnect semantics)
"""

from __future__ import annotations

import struct
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..utils.frames import NULL_FRAME, frame_gt
from ..utils.tracing import trace_log
from .events import (
    Disconnected,
    NetworkInterrupted,
    NetworkResumed,
    SessionState,
    Synchronized,
    Synchronizing,
    NetworkStats,
)
from .time_sync import TimeSync

MAGIC = 0x47A7  # "GGRS-TPU"
HDR = struct.Struct("<HB")

T_SYNC_REQ = 1
T_SYNC_REP = 2
T_INPUT = 3
T_INPUT_ACK = 4
T_QUAL_REQ = 5
T_QUAL_REP = 6
T_KEEP_ALIVE = 7
T_CHECKSUM = 8
# disconnect-frame consensus (GGPO-style): when a peer drops a player, it
# announces the last frame it holds a REAL input for; every survivor adopts
# the MINIMUM announced frame so they all bake identical inputs for the dead
# player (without this, survivors that received different amounts of the
# dying peer's stream diverge permanently)
T_DISC_NOTICE = 9

# Wire protocol version, carried in the sync handshake (REQ and REP both
# append version:u8 after the nonce).  A peer speaking a different version —
# or a pre-versioning build whose sync messages are 4 bytes — never gets a
# valid reply, so the pair stalls in SYNCHRONIZING instead of mis-parsing
# each other's input rows mid-game.  Bump on ANY wire-format change (shared
# with native/ggrs_core/ggrs_core.cc — keep in sync).
PROTOCOL_VERSION = 1

S_SYNC_REQ = struct.Struct("<IB")
S_SYNC_REP = struct.Struct("<IB")
_S_SYNC_NONCE = struct.Struct("<I")  # the pre-version prefix
S_INPUT = struct.Struct("<iHibi")
S_INPUT_ACK = struct.Struct("<i")
S_QUAL_REQ = struct.Struct("<Qb")
S_QUAL_REP = struct.Struct("<Q")
S_CHECKSUM = struct.Struct("<iQ")
S_DISC_NOTICE = struct.Struct("<hi")  # (player handle, disconnect frame)

NUM_SYNC_ROUNDTRIPS = 5
SYNC_RETRY_S = 0.06
QUALITY_INTERVAL_S = 0.2
KEEP_ALIVE_S = 0.2
# max contribution of a single inter-poll gap to the attended-quiet clock
# (see PeerEndpoint.__init__ — bounds how much remote silence a host stall
# can fabricate)
ATTENDED_GAP_CAP_S = 0.25
MAX_INPUTS_PER_PACKET = 64


def now_s() -> float:
    """Monotonic seconds (protocol timer clock)."""
    return time.monotonic()


class PeerEndpoint:
    """Protocol state machine for one remote peer address.

    Handles sync, input exchange (with redundancy + ack), quality/ping,
    keepalive/disconnect and checksum reports.  Transport-agnostic: ``send``
    is a callable taking raw bytes."""

    def __init__(
        self,
        send: Callable[[bytes], None],
        input_size: int,
        rng_nonce: int,
        disconnect_timeout_s: float = 2.0,
        disconnect_notify_start_s: float = 0.5,
        addr=None,
    ):
        self.send_raw = send
        self.addr = addr
        self.input_size = input_size
        self.state = SessionState.SYNCHRONIZING
        self._sync_nonce = rng_nonce & 0xFFFFFFFF
        self._sync_remaining = NUM_SYNC_ROUNDTRIPS
        self._last_sync_sent = 0.0
        self.disconnect_timeout_s = disconnect_timeout_s
        self.disconnect_notify_start_s = disconnect_notify_start_s
        self._last_recv = now_s()
        # attended-quiet accounting: remote silence only counts toward the
        # disconnect timeout while the host was actually polling.  Each
        # inter-poll gap contributes at most ATTENDED_GAP_CAP_S, so a host
        # stall (XLA compile of a new program variant, GC pause, debugger)
        # does not read as seconds of remote silence and spuriously drop a
        # live peer.  A genuinely dead peer still times out after
        # ``disconnect_timeout_s`` of attended silence.
        self._quiet_s = 0.0
        self._last_poll = now_s()
        self._last_send = 0.0
        self._last_quality_sent = 0.0
        self.interrupted = False
        self.disconnected = False
        self.events: List = []
        self.time_sync = TimeSync()
        # input plumbing (frames are EFFECTIVE frames, delay already applied)
        self.last_acked = NULL_FRAME  # newest of our inputs the peer has
        self.last_received_frame = NULL_FRAME  # newest peer input we have (max)
        # highest CONTIGUOUSLY received frame — what we ack (acking the max
        # across a chunk-loss gap would stop the sender refilling the gap)
        self.contig_received = NULL_FRAME
        self._contig_anchored = False  # contig holds a real value (it can
        # legitimately be -1 when the peer's stream starts at frame 0)
        self.stream_base = None  # first frame of OUR outbound input stream
        self.on_input: Optional[Callable[[int, bytes], None]] = None
        self.on_stream_base: Optional[Callable[[int], None]] = None
        self.on_checksum: Optional[Callable[[int, int], None]] = None
        self.on_disc_notice: Optional[Callable[[int, int], None]] = None
        self.local_advantage = 0  # set by session before poll
        # stats
        self.ping_s = 0.0
        self.bytes_sent = 0
        self._created = now_s()
        self.send_queue_len = 0
        self.remote_advantage = 0

    # -- sending ------------------------------------------------------------

    def _send(self, t: int, body: bytes = b"") -> None:
        data = HDR.pack(MAGIC, t) + body
        self.bytes_sent += len(data)
        self._last_send = now_s()
        self.send_raw(data)

    def send_inputs(self, pending: List[Tuple[int, bytes]]) -> None:
        """Send all un-acked inputs (redundant packets, chunked).  ``pending``
        is an ascending [(effective_frame, raw_bytes)] list.  Chunking (up to
        4 packets per call) keeps slow receivers — late-joining or lossy
        spectators — from ever seeing a truncation gap they cannot fill."""
        if self.stream_base is None and pending:
            self.stream_base = pending[0][0]
        pending = [p for p in pending if frame_gt(p[0], self.last_acked)]
        self.send_queue_len = len(pending)
        if not pending:
            return
        for c in range(0, min(len(pending), 4 * MAX_INPUTS_PER_PACKET),
                       MAX_INPUTS_PER_PACKET):
            chunk = pending[c:c + MAX_INPUTS_PER_PACKET]
            body = S_INPUT.pack(
                chunk[0][0], len(chunk), self.contig_received,
                int(np.clip(self.local_advantage, -127, 127)),
                self.stream_base,
            )
            body += b"".join(p[1] for p in chunk)
            self._send(T_INPUT, body)

    def send_input_ack(self) -> None:
        self._send(T_INPUT_ACK, S_INPUT_ACK.pack(self.contig_received))

    def send_checksum(self, frame: int, checksum: int) -> None:
        self._send(T_CHECKSUM, S_CHECKSUM.pack(frame, checksum & (2**64 - 1)))

    def send_disc_notice(self, handle: int, frame: int) -> None:
        self._send(T_DISC_NOTICE, S_DISC_NOTICE.pack(handle, frame))

    # -- receiving ----------------------------------------------------------

    def _sync_version_ok(self, body: bytes) -> bool:
        """Validate the version byte of a sync message body.

        Missing (pre-versioning 4-byte message) or mismatched versions fail;
        the caller drops the packet without replying, stalling the
        handshake."""
        if len(body) < S_SYNC_REQ.size:
            ver = None  # pre-versioning peer
        else:
            ver = body[_S_SYNC_NONCE.size]
        if ver == PROTOCOL_VERSION:
            return True
        from .. import telemetry

        telemetry.count(
            "handshake_version_mismatch_total",
            help="sync messages dropped for a wrong/missing protocol version",
            remote_version=("none" if ver is None else ver),
        )
        trace_log(
            "dropping sync message from %s: protocol version %s != %d",
            self.addr, ver, PROTOCOL_VERSION,
        )
        return False

    def handle(self, data: bytes) -> None:
        """Feed one raw datagram through the protocol state machine
        (untrusted input: malformed packets are dropped)."""
        if self.disconnected:
            # once disconnected, always disconnected (ggrs semantics): a late
            # packet from a dropped peer must not mutate input queues — the
            # session may have advanced its confirmed frame past rollback
            # range on the strength of the disconnect
            return
        try:
            self._handle(data)
        except struct.error:
            return  # truncated/malformed packet: drop (UDP is untrusted input)

    def _handle(self, data: bytes) -> None:
        if len(data) < HDR.size:
            return
        magic, t = HDR.unpack_from(data)
        if magic != MAGIC:
            return
        body = data[HDR.size:]
        was_quiet = self.interrupted
        self._last_recv = now_s()
        self._quiet_s = 0.0
        self._last_poll = self._last_recv  # the gap ending here held a packet
        if self.interrupted:
            self.interrupted = False
            self.events.append(NetworkResumed(self.addr))
        if t == T_SYNC_REQ:
            if not self._sync_version_ok(body):
                return  # no reply: a mixed-version pair must stall, not run
            (nonce, _ver) = S_SYNC_REQ.unpack_from(body)
            self._send(T_SYNC_REP, S_SYNC_REP.pack(nonce, PROTOCOL_VERSION))
        elif t == T_SYNC_REP:
            if not self._sync_version_ok(body):
                return
            (nonce, _ver) = S_SYNC_REP.unpack_from(body)
            if self.state == SessionState.SYNCHRONIZING and nonce == self._sync_nonce:
                self._sync_remaining -= 1
                self._sync_nonce = (self._sync_nonce * 6364136223846793005 + 1) & 0xFFFFFFFF
                self.events.append(
                    Synchronizing(
                        self.addr,
                        NUM_SYNC_ROUNDTRIPS,
                        NUM_SYNC_ROUNDTRIPS - self._sync_remaining,
                    )
                )
                if self._sync_remaining <= 0:
                    self.state = SessionState.RUNNING
                    self.events.append(Synchronized(self.addr))
                else:
                    # continue the handshake immediately (RTT-bound, not
                    # retry-timer-bound); the timer only covers loss
                    self._last_sync_sent = now_s()
                    self._send(
                        T_SYNC_REQ,
                        S_SYNC_REQ.pack(self._sync_nonce, PROTOCOL_VERSION),
                    )
        elif t == T_INPUT:
            start, count, ack, adv, base = S_INPUT.unpack_from(body)
            self._note_ack(ack)
            self.time_sync.note_remote(adv)
            self.remote_advantage = adv
            if not self._contig_anchored:
                # anchor just below the peer's first-ever frame so only
                # ranges connected to the true stream start advance the ack
                self._contig_anchored = True
                self.contig_received = base - 1
                if self.on_stream_base:
                    self.on_stream_base(base)
            payload = body[S_INPUT.size:]
            end = NULL_FRAME
            for i in range(count):
                f = start + i
                raw = payload[i * self.input_size:(i + 1) * self.input_size]
                if len(raw) < self.input_size:
                    break
                end = f
                if frame_gt(f, self.contig_received):
                    if self.last_received_frame == NULL_FRAME or frame_gt(
                        f, self.last_received_frame
                    ):
                        self.last_received_frame = f
                    if self.on_input:
                        self.on_input(f, raw)
            # packets are contiguous ranges: extend the contiguous mark only
            # if this range connects to it
            if (
                end != NULL_FRAME
                and not frame_gt(start, self.contig_received + 1)
                and frame_gt(end, self.contig_received)
            ):
                self.contig_received = end
        elif t == T_INPUT_ACK:
            (ack,) = S_INPUT_ACK.unpack_from(body)
            self._note_ack(ack)
        elif t == T_QUAL_REQ:
            ts, adv = S_QUAL_REQ.unpack_from(body)
            self.time_sync.note_remote(adv)
            self.remote_advantage = adv
            self._send(T_QUAL_REP, S_QUAL_REP.pack(ts))
        elif t == T_QUAL_REP:
            (ts,) = S_QUAL_REP.unpack_from(body)
            self.ping_s = max(0.0, now_s() - ts / 1e6)
        elif t == T_CHECKSUM:
            frame, checksum = S_CHECKSUM.unpack_from(body)
            if self.on_checksum:
                self.on_checksum(frame, checksum)
        elif t == T_DISC_NOTICE:
            handle, frame = S_DISC_NOTICE.unpack_from(body)
            if self.on_disc_notice:
                self.on_disc_notice(handle, frame)
        # T_KEEP_ALIVE: recv timestamp update is enough

    def _note_ack(self, ack: int) -> None:
        if ack != NULL_FRAME and (
            self.last_acked == NULL_FRAME or frame_gt(ack, self.last_acked)
        ):
            self.last_acked = ack

    # -- periodic driving ---------------------------------------------------

    def poll(self) -> None:
        """Advance timers: sync retries, quality reports, keepalive,
        disconnect detection."""
        t = now_s()
        gap = max(t - self._last_poll, 0.0)
        self._last_poll = t
        if self.disconnected:
            return
        # silence accrues per attended poll, capped per gap: a multi-second
        # host stall (e.g. jit compile of a new resim variant) contributes at
        # most ATTENDED_GAP_CAP_S — and never more than half the timeout, so
        # no single stall can trip even an aggressively short timeout
        self._quiet_s += min(
            gap, ATTENDED_GAP_CAP_S, 0.5 * self.disconnect_timeout_s
        )
        if self.state == SessionState.SYNCHRONIZING:
            if t - self._last_sync_sent >= SYNC_RETRY_S:
                self._last_sync_sent = t
                self._send(
                    T_SYNC_REQ,
                    S_SYNC_REQ.pack(self._sync_nonce, PROTOCOL_VERSION),
                )
            return
        if t - self._last_quality_sent >= QUALITY_INTERVAL_S:
            self._last_quality_sent = t
            self._send(
                T_QUAL_REQ,
                S_QUAL_REQ.pack(
                    int(t * 1e6), int(np.clip(self.local_advantage, -127, 127))
                ),
            )
        if t - self._last_send >= KEEP_ALIVE_S:
            # keepalives double as input acks: a stalled peer that sends no
            # INPUT packets must still acknowledge what it received
            if self.last_received_frame != NULL_FRAME:
                self.send_input_ack()
            else:
                self._send(T_KEEP_ALIVE)
        quiet = self._quiet_s
        if quiet >= self.disconnect_timeout_s:
            self.disconnected = True
            self.events.append(Disconnected(self.addr))
        elif quiet >= self.disconnect_notify_start_s and not self.interrupted:
            self.interrupted = True
            self.events.append(
                NetworkInterrupted(
                    self.addr, int(self.disconnect_timeout_s * 1000)
                )
            )

    def stats(self) -> NetworkStats:
        """NetworkStats snapshot for this endpoint."""
        elapsed = max(now_s() - self._created, 1e-6)
        return NetworkStats(
            ping_ms=self.ping_s * 1e3,
            send_queue_len=self.send_queue_len,
            kbps_sent=self.bytes_sent * 8 / 1000 / elapsed,
            local_frames_behind=-self.time_sync.local_advantage(),
            remote_frames_behind=-self.remote_advantage,
        )
