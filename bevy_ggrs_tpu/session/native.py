"""NativeP2PSession — ctypes binding to the C++ host runtime.

Wraps ``native/libggrs_core.so`` (see native/ggrs_core/ggrs_core.h) behind
the same session interface the driver consumes as the pure-Python
:class:`~bevy_ggrs_tpu.session.p2p.P2PSession`, so the two are drop-in
interchangeable — and wire-compatible, a native peer can play a Python peer.
The native core owns the socket, protocol, input queues, and the
advance/rollback decision; Python only moves request buffers and checksums.
"""

from __future__ import annotations

import ctypes as C
import os
import subprocess
from typing import List, Optional

import numpy as np

from .. import telemetry
from .events import (
    DesyncDetected,
    DesyncDetection,
    Disconnected,
    InvalidRequestError,
    NetworkInterrupted,
    NetworkResumed,
    NetworkStats,
    NotSynchronizedError,
    PlayerType,
    PredictionThresholdError,
    SessionState,
    Synchronized,
    Synchronizing,
)
from .requests import (
    AdvanceRequest,
    LoadRequest,
    RollbackCause,
    SaveCell,
    SaveRequest,
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SO_PATH = os.path.join(_REPO_ROOT, "native", "libggrs_core.so")

_OK = 0
_ERR_PREDICTION = -1
_ERR_NOT_SYNC = -2
_ERR_INVALID = -3

_EV_SYNCING, _EV_SYNCED, _EV_DISC, _EV_INT, _EV_RES, _EV_DESYNC = range(6)

_lib: Optional[C.CDLL] = None


def _build_if_needed() -> None:
    if not os.path.exists(_SO_PATH):
        subprocess.run(
            ["make", "-C", os.path.join(_REPO_ROOT, "native")],
            check=True,
            capture_output=True,
        )


def load_library() -> C.CDLL:
    """Load (building if needed) libggrs_core.so and bind its C API."""
    global _lib
    if _lib is not None:
        return _lib
    _build_if_needed()
    lib = C.CDLL(_SO_PATH)
    P = C.c_void_p
    lib.ggrs_p2p_create.restype = P
    lib.ggrs_p2p_create.argtypes = [C.c_int, C.c_int, C.c_uint16, C.c_int,
                                    C.c_int, C.c_int, C.c_double, C.c_double]
    lib.ggrs_p2p_add_player.argtypes = [P, C.c_int, C.c_int, C.c_char_p, C.c_uint16]
    lib.ggrs_p2p_start.argtypes = [P]
    lib.ggrs_p2p_destroy.argtypes = [P]
    lib.ggrs_p2p_local_port.restype = C.c_uint16
    lib.ggrs_p2p_local_port.argtypes = [P]
    lib.ggrs_p2p_poll.argtypes = [P]
    lib.ggrs_p2p_state.argtypes = [P]
    lib.ggrs_p2p_add_local_input.argtypes = [P, C.c_int, C.c_char_p]
    lib.ggrs_p2p_advance.argtypes = [P, C.POINTER(C.c_int32), C.c_int,
                                     C.POINTER(C.c_uint8), C.c_int,
                                     C.POINTER(C.c_int), C.POINTER(C.c_int)]
    lib.ggrs_p2p_current_frame.restype = C.c_int32
    lib.ggrs_p2p_current_frame.argtypes = [P]
    lib.ggrs_p2p_confirmed_frame.restype = C.c_int32
    lib.ggrs_p2p_confirmed_frame.argtypes = [P]
    lib.ggrs_p2p_frames_ahead.argtypes = [P]
    lib.ggrs_p2p_max_prediction.argtypes = [P]
    lib.ggrs_p2p_num_players.argtypes = [P]
    lib.ggrs_p2p_local_handles.argtypes = [P, C.POINTER(C.c_int32), C.c_int]
    lib.ggrs_p2p_next_event.argtypes = [P, C.POINTER(C.c_int32),
                                        C.POINTER(C.c_int32), C.POINTER(C.c_uint64),
                                        C.POINTER(C.c_uint64),
                                        C.c_char_p, C.c_int]
    lib.ggrs_p2p_push_checksum.argtypes = [P, C.c_int32, C.c_uint64]
    lib.ggrs_p2p_stats.argtypes = [P, C.c_int, C.POINTER(C.c_double),
                                   C.POINTER(C.c_int), C.POINTER(C.c_double),
                                   C.POINTER(C.c_int), C.POINTER(C.c_int)]
    _bind_spectator(lib)
    _lib = lib
    return lib


def _bind_spectator(lib: C.CDLL) -> None:
    P = C.c_void_p
    lib.ggrs_spectator_create.restype = P
    lib.ggrs_spectator_create.argtypes = [C.c_int, C.c_int, C.c_uint16,
                                          C.c_char_p, C.c_uint16,
                                          C.c_double, C.c_double, C.c_int]
    lib.ggrs_spectator_destroy.argtypes = [P]
    lib.ggrs_spectator_local_port.restype = C.c_uint16
    lib.ggrs_spectator_local_port.argtypes = [P]
    lib.ggrs_spectator_poll.argtypes = [P]
    lib.ggrs_spectator_state.argtypes = [P]
    lib.ggrs_spectator_current_frame.restype = C.c_int32
    lib.ggrs_spectator_current_frame.argtypes = [P]
    lib.ggrs_spectator_frames_behind.restype = C.c_int32
    lib.ggrs_spectator_frames_behind.argtypes = [P]
    lib.ggrs_spectator_advance.argtypes = [P, C.POINTER(C.c_int32), C.c_int,
                                           C.POINTER(C.c_uint8), C.c_int,
                                           C.POINTER(C.c_int), C.POINTER(C.c_int)]
    lib.ggrs_spectator_next_event.argtypes = [P, C.POINTER(C.c_int32),
                                              C.POINTER(C.c_int32),
                                              C.POINTER(C.c_uint64),
                                              C.POINTER(C.c_uint64),
                                              C.c_char_p, C.c_int]


def native_available() -> bool:
    """True if the native core library can be loaded/built."""
    try:
        load_library()
        return True
    except Exception:
        return False


class NativeP2PSession:
    """P2P session backed by the native C++ core (GGRS session surface)."""

    def __init__(
        self,
        num_players: int,
        players,  # List[Player]
        local_port: int = 0,
        input_shape=(),
        input_dtype=np.uint8,
        max_prediction: int = 8,
        input_delay: int = 0,
        desync_detection: DesyncDetection = DesyncDetection.OFF,
        disconnect_timeout_s: float = 2.0,
        disconnect_notify_start_s: float = 0.5,
    ):
        self._lib = load_library()
        self._num_players = num_players
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.input_size = int(np.prod(self.input_shape, dtype=int) or 1) * self.input_dtype.itemsize
        self._max_prediction = max_prediction
        self.desync_detection = desync_detection
        interval = desync_detection.interval if desync_detection.enabled else 0
        self._s = self._lib.ggrs_p2p_create(
            num_players, self.input_size, local_port, max_prediction,
            input_delay, interval, disconnect_timeout_s, disconnect_notify_start_s,
        )
        if not self._s:
            raise InvalidRequestError(f"could not bind UDP port {local_port}")
        # remote player handles, for samplers and rollback-cause attribution
        # (the native core does not export per-load blame, so the decode path
        # below blames the unique remote handle when there is exactly one)
        self._remote_handles = sorted(
            p.handle for p in players if p.kind == PlayerType.REMOTE
        )
        for p in players:
            if p.kind == PlayerType.LOCAL:
                rc = self._lib.ggrs_p2p_add_player(self._s, 0, p.handle, None, 0)
            elif p.kind == PlayerType.REMOTE:
                ip, port = p.address
                rc = self._lib.ggrs_p2p_add_player(
                    self._s, 1, p.handle, ip.encode(), int(port)
                )
            else:  # spectator: host streams confirmed all-player inputs
                ip, port = p.address
                rc = self._lib.ggrs_p2p_add_player(
                    self._s, 2, p.handle, ip.encode(), int(port)
                )
            if rc != _OK:
                raise InvalidRequestError(f"add_player failed rc={rc}")
        if self._lib.ggrs_p2p_start(self._s) != _OK:
            raise InvalidRequestError("incomplete player set")
        # request scratch buffers
        self._req_cap = 4096
        self._req_buf = (C.c_int32 * self._req_cap)()
        self._input_cap = 1 << 20
        self._input_buf = (C.c_uint8 * self._input_cap)()
        self._pending_checksums = {}  # frame -> provider
        self.events_buf: List = []

    def __del__(self):
        try:
            if getattr(self, "_s", None):
                self._lib.ggrs_p2p_destroy(self._s)
                self._s = None
        except Exception:
            pass

    # -- GGRS surface --------------------------------------------------------

    def local_port(self) -> int:
        return int(self._lib.ggrs_p2p_local_port(self._s))

    def num_players(self) -> int:
        return self._num_players

    def max_prediction(self) -> int:
        return self._max_prediction

    def confirmed_frame(self) -> int:
        return int(self._lib.ggrs_p2p_confirmed_frame(self._s))

    def current_frame(self) -> int:
        return int(self._lib.ggrs_p2p_current_frame(self._s))

    def frames_ahead(self) -> int:
        return int(self._lib.ggrs_p2p_frames_ahead(self._s))

    def current_state(self) -> SessionState:
        return (
            SessionState.RUNNING
            if self._lib.ggrs_p2p_state(self._s) == 1
            else SessionState.SYNCHRONIZING
        )

    def local_player_handles(self) -> List[int]:
        """Handles owned by this session."""
        buf = (C.c_int32 * self._num_players)()
        n = self._lib.ggrs_p2p_local_handles(self._s, buf, self._num_players)
        return [int(buf[i]) for i in range(n)]

    def remote_player_handles(self) -> List[int]:
        """Handles owned by remote peers, ascending (sampler surface)."""
        return list(self._remote_handles)

    def poll_remote_clients(self) -> None:
        """Drive the native socket/protocol; drain events and checksums."""
        self._lib.ggrs_p2p_poll(self._s)
        self._flush_checksums()
        self._drain_events()

    def add_local_input(self, handle: int, value) -> None:
        """Stage this tick's input for a local handle."""
        raw = np.asarray(value, self.input_dtype).reshape(self.input_shape)
        rc = self._lib.ggrs_p2p_add_local_input(
            self._s, handle, np.ascontiguousarray(raw).tobytes()
        )
        if rc == _ERR_NOT_SYNC:
            raise NotSynchronizedError()
        if rc != _OK:
            raise InvalidRequestError(f"add_local_input rc={rc}")

    def advance_frame(self) -> List:
        """Run the native advance/rollback decision; decode the request stream."""
        # the native core does not export per-rollback blame, so LOAD decode
        # below reconstructs lateness from the pre-advance frame and blames
        # the unique remote handle when there is exactly one
        cur_before = self.current_frame()
        n_req = C.c_int(0)
        n_in = C.c_int(0)
        rc = self._lib.ggrs_p2p_advance(
            self._s, self._req_buf, self._req_cap,
            self._input_buf, self._input_cap, C.byref(n_req), C.byref(n_in),
        )
        if rc == _ERR_PREDICTION:
            raise PredictionThresholdError()
        if rc == _ERR_NOT_SYNC:
            raise NotSynchronizedError()
        if rc != _OK:
            raise InvalidRequestError(f"advance_frame rc={rc}")
        words = np.ctypeslib.as_array(self._req_buf, (n_req.value,))
        ibytes = bytes(bytearray(self._input_buf[: n_in.value]))
        requests: List = []
        i = 0
        off = 0
        P = self._num_players
        row = P * self.input_size
        while i < n_req.value:
            t = int(words[i])
            if t == 0:  # SAVE
                frame = int(words[i + 1])
                requests.append(SaveRequest(frame, SaveCell(self, frame)))
                i += 2
            elif t == 1:  # LOAD
                frame = int(words[i + 1])
                blamed = (
                    self._remote_handles[0]
                    if len(self._remote_handles) == 1
                    else "unknown"
                )
                requests.append(LoadRequest(frame, cause=RollbackCause(
                    handle=blamed, frame=frame,
                    lateness=max(0, cur_before - frame),
                    mismatch=blamed != "unknown",
                    kind="misprediction" if blamed != "unknown" else "unknown",
                )))
                i += 2
            else:  # ADVANCE
                status = np.array(words[i + 2 : i + 2 + P], np.int8)
                chunk = ibytes[off : off + row]
                off += row
                inputs = np.frombuffer(chunk, self.input_dtype).reshape(
                    (P, *self.input_shape)
                )
                requests.append(AdvanceRequest(inputs.copy(), status))
                i += 2 + P
        return requests

    def events(self):
        """Drain pending session events."""
        out, self.events_buf = self.events_buf, []
        return out

    def network_stats(self, handle: int) -> NetworkStats:
        """Ping/queue/kbps/frames-behind for a remote handle.

        Local, unknown, and disconnected handles return a zeroed snapshot
        with ``is_live=False`` instead of raising, so samplers can sweep
        every handle without exception handling."""
        ping = C.c_double(0)
        q = C.c_int(0)
        kbps = C.c_double(0)
        lfb = C.c_int(0)
        rfb = C.c_int(0)
        rc = self._lib.ggrs_p2p_stats(
            self._s, handle, C.byref(ping), C.byref(q), C.byref(kbps),
            C.byref(lfb), C.byref(rfb),
        )
        if rc != _OK:
            return NetworkStats(is_live=False)
        return NetworkStats(
            ping_ms=ping.value, send_queue_len=q.value, kbps_sent=kbps.value,
            local_frames_behind=lfb.value, remote_frames_behind=rfb.value,
        )

    # -- checksum plumbing (desync detection) --------------------------------

    def _on_cell_saved(self, frame: int, provider) -> None:
        if self.desync_detection.enabled and frame % self.desync_detection.interval == 0:
            self._pending_checksums[frame] = provider

    def _flush_checksums(self) -> None:
        if not self.desync_detection.enabled:
            return
        confirmed = self.confirmed_frame()
        for frame in sorted(self._pending_checksums):
            if frame > confirmed:
                break
            provider = self._pending_checksums[frame]
            peek = getattr(provider, "peek", None)
            value = peek() if peek is not None else None
            if peek is not None and value is None:
                if frame > confirmed - self._max_prediction:
                    # async copy still in flight and the frame is well inside
                    # the window — the native core accepts late checksums, so
                    # retry next poll instead of blocking the tick
                    continue
                value = provider()  # leaving the window: force (flush)
            elif peek is None:
                value = provider()
            del self._pending_checksums[frame]
            if value is not None:
                self._lib.ggrs_p2p_push_checksum(self._s, frame, value & (2**64 - 1))

    def _drain_events(self) -> None:
        kind = C.c_int32(0)
        a = C.c_int32(0)
        b = C.c_uint64(0)
        b2 = C.c_uint64(0)
        addr = C.create_string_buffer(64)
        while self._lib.ggrs_p2p_next_event(
            self._s, C.byref(kind), C.byref(a), C.byref(b), C.byref(b2), addr, 64
        ):
            s = addr.value.decode()
            k = kind.value
            if k == _EV_SYNCING:
                self.events_buf.append(Synchronizing(s, int(b.value), a.value))
            elif k == _EV_SYNCED:
                self.events_buf.append(Synchronized(s))
            elif k == _EV_DISC:
                self.events_buf.append(Disconnected(s))
            elif k == _EV_INT:
                self.events_buf.append(NetworkInterrupted(s, a.value))
            elif k == _EV_RES:
                self.events_buf.append(NetworkResumed(s))
            elif k == _EV_DESYNC:
                telemetry.count(
                    "checksum_mismatch_total",
                    help="frames whose checksums disagreed", kind="p2p",
                )
                self.events_buf.append(
                    DesyncDetected(
                        frame=a.value, local_checksum=int(b2.value),
                        remote_checksum=int(b.value), addr=s,
                    )
                )


class NativeSpectatorSession:
    """Spectator session backed by the C++ core: follows a host's confirmed
    input stream, never predicts (GGRS session surface)."""

    is_spectator = True

    def __init__(
        self,
        num_players: int,
        host_addr,
        local_port: int = 0,
        input_shape=(),
        input_dtype=np.uint8,
        disconnect_timeout_s: float = 2.0,
        disconnect_notify_start_s: float = 0.5,
        catchup_speed: int = 1,
    ):
        self._lib = load_library()
        self._num_players = num_players
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.input_size = int(np.prod(self.input_shape, dtype=int) or 1) * self.input_dtype.itemsize
        ip, port = host_addr
        self._s = self._lib.ggrs_spectator_create(
            num_players, self.input_size, local_port, ip.encode(), int(port),
            disconnect_timeout_s, disconnect_notify_start_s, catchup_speed,
        )
        if not self._s:
            raise InvalidRequestError(f"could not bind UDP port {local_port}")
        self._req_cap = 1024
        self._req_buf = (C.c_int32 * self._req_cap)()
        self._input_cap = 1 << 18
        self._input_buf = (C.c_uint8 * self._input_cap)()
        self.events_buf: List = []

    def __del__(self):
        try:
            if getattr(self, "_s", None):
                self._lib.ggrs_spectator_destroy(self._s)
                self._s = None
        except Exception:
            pass

    def local_port(self) -> int:
        """Bound UDP port (useful with port 0 auto-assignment)."""
        return int(self._lib.ggrs_spectator_local_port(self._s))

    def num_players(self) -> int:
        return self._num_players

    def max_prediction(self) -> int:
        return 0  # spectators never predict

    def confirmed_frame(self) -> int:
        return self.current_frame() - 1

    def current_frame(self) -> int:
        """Next frame to replay."""
        return int(self._lib.ggrs_spectator_current_frame(self._s))

    def frames_behind_host(self) -> int:
        """How far the host's confirmed stream is ahead of us."""
        return int(self._lib.ggrs_spectator_frames_behind(self._s))

    def current_state(self) -> SessionState:
        return (
            SessionState.RUNNING
            if self._lib.ggrs_spectator_state(self._s) == 1
            else SessionState.SYNCHRONIZING
        )

    def poll_remote_clients(self) -> None:
        """Drive the native socket/protocol; drain events."""
        self._lib.ggrs_spectator_poll(self._s)
        self._drain_events()

    def advance_frame(self) -> List:
        """Replay the next confirmed frame(s) from the host stream."""
        n_req = C.c_int(0)
        n_in = C.c_int(0)
        rc = self._lib.ggrs_spectator_advance(
            self._s, self._req_buf, self._req_cap,
            self._input_buf, self._input_cap, C.byref(n_req), C.byref(n_in),
        )
        if rc == _ERR_PREDICTION:
            raise PredictionThresholdError()
        if rc == _ERR_NOT_SYNC:
            raise NotSynchronizedError()
        if rc != _OK:
            raise InvalidRequestError(f"spectator advance rc={rc}")
        words = np.ctypeslib.as_array(self._req_buf, (n_req.value,))
        ibytes = bytes(bytearray(self._input_buf[: n_in.value]))
        P = self._num_players
        row = P * self.input_size
        requests: List = []
        i = 0
        off = 0
        while i < n_req.value:
            status = np.array(words[i + 2 : i + 2 + P], np.int8)
            chunk = ibytes[off : off + row]
            off += row
            inputs = np.frombuffer(chunk, self.input_dtype).reshape(
                (P, *self.input_shape)
            )
            requests.append(AdvanceRequest(inputs.copy(), status))
            i += 2 + P
        return requests

    def events(self):
        """Drain pending session events."""
        out, self.events_buf = self.events_buf, []
        return out

    def _drain_events(self) -> None:
        kind = C.c_int32(0)
        a = C.c_int32(0)
        b = C.c_uint64(0)
        b2 = C.c_uint64(0)
        addr = C.create_string_buffer(64)
        while self._lib.ggrs_spectator_next_event(
            self._s, C.byref(kind), C.byref(a), C.byref(b), C.byref(b2), addr, 64
        ):
            s = addr.value.decode()
            k = kind.value
            if k == _EV_SYNCING:
                self.events_buf.append(Synchronizing(s, int(b.value), a.value))
            elif k == _EV_SYNCED:
                self.events_buf.append(Synchronized(s))
            elif k == _EV_DISC:
                self.events_buf.append(Disconnected(s))
            elif k == _EV_INT:
                self.events_buf.append(NetworkInterrupted(s, a.value))
            elif k == _EV_RES:
                self.events_buf.append(NetworkResumed(s))
