"""SessionBuilder — the fluent session construction surface (SURVEY §2.3:
``with_num_players``, ``with_max_prediction_window``, ``with_input_delay``,
``with_check_distance``, ``with_desync_detection_mode``, ``add_player``,
``start_{p2p,synctest,spectator}_session``)."""

from __future__ import annotations

from typing import Any, List, Tuple

import numpy as np

from .events import DesyncDetection, InvalidRequestError, Player, PlayerType
from .p2p import P2PSession
from .spectator import SpectatorSession
from .synctest import SyncTestSession


class SessionBuilder:
    """Fluent session construction (see module docstring for the surface)."""
    def __init__(self, input_shape: Tuple[int, ...] = (), input_dtype=np.uint8):
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self._num_players = 2
        self._max_prediction = 8
        self._input_delay = 0
        self._check_distance = 2
        self._desync = DesyncDetection.OFF
        self._players: List[Player] = []
        self._disconnect_timeout_s = 2.0
        self._disconnect_notify_start_s = 0.5
        self._catchup_speed = 1
        self._input_predictor = None
        self._eager_checksums = False

    @classmethod
    def for_app(cls, app) -> "SessionBuilder":
        """Builder pre-filled with the app's input spec and player count."""
        b = cls(app.input_shape, app.input_dtype)
        b._num_players = app.num_players
        return b

    def with_num_players(self, n: int) -> "SessionBuilder":
        """Set the total player count (handles 0..n-1)."""
        if n < 1:
            raise InvalidRequestError("num_players must be >= 1")
        self._num_players = n
        return self

    def with_max_prediction_window(self, n: int) -> "SessionBuilder":
        """Frames the session may run ahead of confirmed inputs before stalling."""
        self._max_prediction = n
        return self

    def with_input_delay(self, n: int) -> "SessionBuilder":
        """Frames of local input delay (trades latency for fewer rollbacks)."""
        self._input_delay = n
        return self

    def with_check_distance(self, n: int) -> "SessionBuilder":
        """SyncTest resimulation depth per tick."""
        self._check_distance = n
        return self

    def with_desync_detection_mode(self, mode: DesyncDetection) -> "SessionBuilder":
        """Enable periodic cross-peer checksum comparison (DesyncDetection.on(n))."""
        self._desync = mode
        return self

    def with_input_predictor(self, predictor) -> "SessionBuilder":
        """Override remote-input prediction (the Config::InputPredictor slot,
        SURVEY §2.3); default PredictRepeatLast.  ``predictor(queue, frame)``
        returns the guessed input value."""
        self._input_predictor = predictor
        return self

    def with_eager_checksums(self, eager: bool = True) -> "SessionBuilder":
        """Force desync-detection checksum providers at the tick their frame
        confirms (the pre-pipeline synchronous behavior — the bench's sync
        baseline).  Default off: providers are peeked non-blocking and
        published when the async device->host copy lands."""
        self._eager_checksums = eager
        return self

    def with_disconnect_timeout(self, seconds: float) -> "SessionBuilder":
        """Seconds of peer silence before Disconnected."""
        self._disconnect_timeout_s = seconds
        return self

    def with_disconnect_notify_delay(self, seconds: float) -> "SessionBuilder":
        """Seconds of peer silence before NetworkInterrupted."""
        self._disconnect_notify_start_s = seconds
        return self

    def with_catchup_speed(self, frames_per_tick: int) -> "SessionBuilder":
        """Extra confirmed frames a lagging spectator replays per tick
        (the reference's SessionBuilder::with_catchup_speed; spectator
        sessions only)."""
        if frames_per_tick < 1:
            raise ValueError("catchup_speed must be >= 1")
        self._catchup_speed = frames_per_tick
        return self

    def add_player(self, kind: PlayerType, handle: int, address: Any = None) -> "SessionBuilder":
        """Add a LOCAL/REMOTE player (by handle) or a SPECTATOR (by address)."""
        if kind != PlayerType.SPECTATOR and not (0 <= handle < self._num_players):
            raise InvalidRequestError(
                f"player handle {handle} out of range 0..{self._num_players}"
            )
        if kind in (PlayerType.REMOTE, PlayerType.SPECTATOR) and address is None:
            raise InvalidRequestError(f"{kind} player needs an address")
        self._players.append(Player(kind, handle, address))
        return self

    def start_p2p_session(self, socket) -> P2PSession:
        """Build a python-core P2P session over the given socket."""
        handles = {p.handle for p in self._players if p.kind != PlayerType.SPECTATOR}
        if handles != set(range(self._num_players)):
            raise InvalidRequestError(
                f"players incomplete: have handles {sorted(handles)}"
            )
        return P2PSession(
            num_players=self._num_players,
            players=self._players,
            socket=socket,
            input_shape=self.input_shape,
            input_dtype=self.input_dtype,
            max_prediction=self._max_prediction,
            input_delay=self._input_delay,
            desync_detection=self._desync,
            disconnect_timeout_s=self._disconnect_timeout_s,
            disconnect_notify_start_s=self._disconnect_notify_start_s,
            input_predictor=self._input_predictor,
            eager_checksums=self._eager_checksums,
        )

    def start_p2p_session_native(self, local_port: int = 0):
        """P2P session backed by the native C++ host runtime
        (native/ggrs_core) — same wire protocol, same request stream."""
        from .native import NativeP2PSession

        handles = {p.handle for p in self._players if p.kind != PlayerType.SPECTATOR}
        if handles != set(range(self._num_players)):
            raise InvalidRequestError(
                f"players incomplete: have handles {sorted(handles)}"
            )
        return NativeP2PSession(
            num_players=self._num_players,
            players=self._players,
            local_port=local_port,
            input_shape=self.input_shape,
            input_dtype=self.input_dtype,
            max_prediction=self._max_prediction,
            input_delay=self._input_delay,
            desync_detection=self._desync,
            disconnect_timeout_s=self._disconnect_timeout_s,
            disconnect_notify_start_s=self._disconnect_notify_start_s,
        )

    def start_synctest_session(self) -> SyncTestSession:
        return SyncTestSession(
            num_players=self._num_players,
            input_shape=self.input_shape,
            input_dtype=self.input_dtype,
            check_distance=self._check_distance,
            input_delay=self._input_delay,
            max_prediction=self._max_prediction,
        )

    def start_spectator_session_native(self, host_addr: Any, local_port: int = 0):
        """Spectator session backed by the native C++ core."""
        from .native import NativeSpectatorSession

        return NativeSpectatorSession(
            num_players=self._num_players,
            host_addr=host_addr,
            local_port=local_port,
            input_shape=self.input_shape,
            input_dtype=self.input_dtype,
            disconnect_timeout_s=self._disconnect_timeout_s,
            disconnect_notify_start_s=self._disconnect_notify_start_s,
            catchup_speed=self._catchup_speed,
        )

    def start_spectator_session(self, host_addr: Any, socket) -> SpectatorSession:
        return SpectatorSession(
            num_players=self._num_players,
            host_addr=host_addr,
            socket=socket,
            input_shape=self.input_shape,
            input_dtype=self.input_dtype,
            disconnect_timeout_s=self._disconnect_timeout_s,
            disconnect_notify_start_s=self._disconnect_notify_start_s,
            catchup_speed=self._catchup_speed,
        )
