"""In-process channel transport — the WebRTC/matchbox-analog alternative
socket (the reference supports swapping `UdpNonBlockingSocket` for matchbox
WebRTC behind the socket trait, README.md:79).  `ChannelNetwork` creates
endpoints addressed by name with optional deterministic latency/loss — a
pluggable `NonBlockingSocket` for tests and simulations that must not touch
real sockets."""

from __future__ import annotations

import random
from collections import deque
from typing import Any, Deque, Dict, List, Tuple


class ChannelNetwork:
    """A little virtual packet network: named endpoints, FIFO per pair,
    optional per-hop latency (in ``deliver`` calls) and loss rate."""

    def __init__(self, latency_hops: int = 0, loss: float = 0.0, seed: int = 0):
        self.latency_hops = latency_hops
        self.loss = loss
        self._rng = random.Random(seed)
        self._queues: Dict[Any, Deque[Tuple[int, Any, bytes]]] = {}
        self._clock = 0

    def endpoint(self, name: Any) -> "ChannelSocket":
        self._queues.setdefault(name, deque())
        return ChannelSocket(self, name)

    def deliver(self) -> None:
        """Advance the virtual network one hop (ages queued packets)."""
        self._clock += 1

    def _send(self, src: Any, dst: Any, data: bytes) -> None:
        if self.loss and self._rng.random() < self.loss:
            return
        q = self._queues.setdefault(dst, deque())
        q.append((self._clock + self.latency_hops, src, data))

    def _recv_all(self, name: Any) -> List[Tuple[Any, bytes]]:
        q = self._queues.setdefault(name, deque())
        out = []
        while q and q[0][0] <= self._clock:
            _, src, data = q.popleft()
            out.append((src, data))
        return out


class ChannelSocket:
    """NonBlockingSocket over a ChannelNetwork."""

    def __init__(self, net: ChannelNetwork, name: Any):
        self.net = net
        self.name = name

    @property
    def local_addr(self) -> Any:
        return self.name

    def send_to(self, data: bytes, addr: Any) -> None:
        self.net._send(self.name, addr, data)

    def receive_all(self) -> List[Tuple[Any, bytes]]:
        return self.net._recv_all(self.name)
