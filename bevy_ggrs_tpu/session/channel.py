"""In-process channel transport — the WebRTC/matchbox-analog alternative
socket (the reference supports swapping `UdpNonBlockingSocket` for matchbox
WebRTC behind the socket trait, README.md:79).  `ChannelNetwork` creates
endpoints addressed by name with optional deterministic latency/loss — a
pluggable `NonBlockingSocket` for tests and simulations that must not touch
real sockets."""

from __future__ import annotations

import random
from typing import Any, Dict, List, Tuple


class ChannelNetwork:
    """A little virtual packet network: named endpoints, optional per-hop
    latency (in ``deliver`` calls), loss rate, and reorder jitter (extra
    random hops per packet -> out-of-order delivery)."""

    def __init__(self, latency_hops: int = 0, loss: float = 0.0, seed: int = 0,
                 jitter_hops: int = 0):
        self.latency_hops = latency_hops
        self.loss = loss
        self.jitter_hops = jitter_hops
        self._rng = random.Random(seed)
        self._queues: Dict[Any, list] = {}
        self._clock = 0

    def endpoint(self, name: Any) -> "ChannelSocket":
        """Create/fetch the named endpoint's socket."""
        self._queues.setdefault(name, [])
        return ChannelSocket(self, name)

    def deliver(self) -> None:
        """Advance the virtual network one hop (ages queued packets)."""
        self._clock += 1

    def _send(self, src: Any, dst: Any, data: bytes) -> None:
        if self.loss and self._rng.random() < self.loss:
            return
        delay = self.latency_hops
        if self.jitter_hops:
            delay += self._rng.randint(0, self.jitter_hops)
        q = self._queues.setdefault(dst, [])
        q.append((self._clock + delay, src, data))

    def _recv_all(self, name: Any) -> List[Tuple[Any, bytes]]:
        q = self._queues.setdefault(name, [])
        due = [(t, src, d) for (t, src, d) in q if t <= self._clock]
        q[:] = [(t, src, d) for (t, src, d) in q if t > self._clock]
        return [(src, d) for (_, src, d) in due]


class ChannelSocket:
    """NonBlockingSocket over a ChannelNetwork."""

    def __init__(self, net: ChannelNetwork, name: Any):
        self.net = net
        self.name = name

    @property
    def local_addr(self) -> Any:
        return self.name

    def send_to(self, data: bytes, addr: Any) -> None:
        self.net._send(self.name, addr, data)

    def receive_all(self) -> List[Tuple[Any, bytes]]:
        return self.net._recv_all(self.name)
