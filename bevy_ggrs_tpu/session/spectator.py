"""SpectatorSession — follow a host's session without playing.

Receives confirmed all-player inputs streamed by the host's P2PSession and
replays them; never predicts (the driver forces MaxPredictionWindow(0),
/root/reference/src/schedule_systems.rs:200).  ``advance_frame`` raises
PredictionThreshold while the next confirmed input has not arrived
(the driver logs and skips, :129-135)."""

from __future__ import annotations

import random
from typing import Any, Dict, List

import numpy as np

from .. import telemetry
from ..utils.frames import NULL_FRAME, frame_add, frame_diff
from .events import (
    NetworkStats,
    NotSynchronizedError,
    PredictionThresholdError,
    SessionState,
)
from .protocol import PeerEndpoint
from .requests import AdvanceRequest


class SpectatorSession:
    """Replays host-confirmed inputs; never predicts (see module docstring)."""
    is_spectator = True

    def __init__(
        self,
        num_players: int,
        host_addr: Any,
        socket,
        input_shape=(),
        input_dtype=np.uint8,
        disconnect_timeout_s: float = 2.0,
        disconnect_notify_start_s: float = 0.5,
        catchup_speed: int = 1,
    ):
        self._num_players = num_players
        self.host_addr = host_addr
        self.socket = socket
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.input_size = int(np.prod(self.input_shape, dtype=int) or 1) * self.input_dtype.itemsize
        self.current_frame = 0
        self.catchup_speed = catchup_speed
        self.events_buf: List = []
        # frame -> (inputs [P, *shape], statuses int8[P])
        self._inputs: Dict[int, tuple] = {}
        self.endpoint = PeerEndpoint(
            send=lambda data: self.socket.send_to(data, host_addr),
            # full row: all-player inputs + one status byte per player (the
            # host streams the statuses its own sim used, so
            # status-sensitive models replay bit-identically — e.g.
            # DISCONNECTED for a dead player's post-consensus frames)
            input_size=self.input_size * num_players + num_players,
            # bgt: ignore[BGT041]: handshake nonce — intentionally unique per
            # process (stale-session detection); never enters the simulation
            rng_nonce=random.getrandbits(32),
            disconnect_timeout_s=disconnect_timeout_s,
            disconnect_notify_start_s=disconnect_notify_start_s,
            addr=host_addr,
        )
        self.endpoint.on_input = self._on_input

    def _on_input(self, frame: int, raw: bytes) -> None:
        n = self.input_size * self._num_players
        inputs = np.frombuffer(raw[:n], self.input_dtype).reshape(
            (self._num_players, *self.input_shape)
        )
        status = np.frombuffer(
            raw[n:n + self._num_players], np.int8
        ).copy()
        self._inputs[frame] = (inputs, status)

    # -- GGRS session surface ----------------------------------------------

    def num_players(self) -> int:
        return self._num_players

    def max_prediction(self) -> int:
        return 0  # spectators never predict (schedule_systems.rs:200)

    def confirmed_frame(self) -> int:
        return frame_add(self.current_frame, -1)

    def current_state(self) -> SessionState:
        return (
            SessionState.RUNNING
            if self.endpoint.state == SessionState.RUNNING
            else SessionState.SYNCHRONIZING
        )

    def frames_behind_host(self) -> int:
        """How far the host's confirmed stream is ahead of us."""
        last = self.endpoint.last_received_frame
        if last == NULL_FRAME:
            return 0
        return max(0, frame_diff(last, self.current_frame))

    def events(self):
        """Drain pending session events."""
        out = list(self.endpoint.events)
        self.endpoint.events.clear()
        out += self.events_buf
        self.events_buf = []
        return out

    def network_stats(self, handle: int = 0) -> NetworkStats:
        return self.endpoint.stats()

    def poll_remote_clients(self) -> None:
        """Drain the socket, drive the host endpoint, ack received inputs."""
        for addr, data in self.socket.receive_all():
            if addr == self.host_addr:
                self.endpoint.handle(data)
        self.endpoint.poll()
        if self.endpoint.state == SessionState.RUNNING:
            self.endpoint.send_input_ack()

    def advance_frame(self) -> List:
        """Replay the next confirmed frame(s); raises PredictionThreshold while waiting."""
        if self.current_state() != SessionState.RUNNING:
            raise NotSynchronizedError()
        if self.current_frame not in self._inputs:
            raise PredictionThresholdError()  # waiting for host input
        # catch-up: when lagging the host, replay extra confirmed frames this
        # tick (the reference spectator's catchup behavior)
        n = 1
        if self.frames_behind_host() > 2:
            n += max(self.catchup_speed, 0)
            telemetry.count(
                "spectator_catchup_ticks_total",
                help="spectator ticks that replayed extra frames to catch up",
            )
            telemetry.record(
                "spectator_catchup", frame=self.current_frame,
                behind=self.frames_behind_host(), replaying=n,
            )
        requests: List = []
        for _ in range(n):
            if self.current_frame not in self._inputs:
                break
            inputs, status = self._inputs.pop(self.current_frame)
            self.current_frame = frame_add(self.current_frame, 1)
            requests.append(AdvanceRequest(np.asarray(inputs), status))
        return requests
