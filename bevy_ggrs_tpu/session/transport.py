"""Pluggable non-blocking transport.

Mirrors the reference's socket abstraction: a ``NonBlockingSocket`` trait
with a UDP implementation (``UdpNonBlockingSocket::bind_to_port``,
/root/reference/tests/p2p.rs:107) and room for alternatives (the reference
supports matchbox WebRTC; here any object with the same two methods works —
e.g. an in-process channel for deterministic tests)."""

from __future__ import annotations

import socket
from typing import Any, List, Protocol, Tuple


class NonBlockingSocket(Protocol):
    """Transport protocol: send_to(data, addr) + receive_all()."""
    def send_to(self, data: bytes, addr: Any) -> None: ...

    def receive_all(self) -> List[Tuple[Any, bytes]]: ...


class UdpNonBlockingSocket:
    """Non-blocking UDP socket bound to a local port."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind((host, port))

    @classmethod
    def bind_to_port(cls, port: int) -> "UdpNonBlockingSocket":
        return cls(port)

    @property
    def local_addr(self) -> Tuple[str, int]:
        return self._sock.getsockname()

    def send_to(self, data: bytes, addr) -> None:
        try:
            self._sock.sendto(data, addr)
        except (BlockingIOError, OSError):
            pass  # non-blocking: drop on full buffer (UDP semantics)

    def receive_all(self) -> List[Tuple[Any, bytes]]:
        """Drain every pending datagram -> [(addr, bytes)]."""
        out = []
        while True:
            try:
                data, addr = self._sock.recvfrom(65536)
            except (BlockingIOError, OSError):
                break
            out.append((addr, data))
        return out

    def close(self) -> None:
        self._sock.close()
