"""Pluggable non-blocking transport.

Mirrors the reference's socket abstraction: a ``NonBlockingSocket`` trait
with a UDP implementation (``UdpNonBlockingSocket::bind_to_port``,
/root/reference/tests/p2p.rs:107) and room for alternatives (the reference
supports matchbox WebRTC; here any object with the same two methods works —
e.g. an in-process channel for deterministic tests, or the framed-TCP
transport below for UDP-hostile networks)."""

from __future__ import annotations

import socket
from collections import deque
from typing import Any, List, Protocol, Tuple


class NonBlockingSocket(Protocol):
    """Transport protocol: send_to(data, addr) + receive_all()."""
    def send_to(self, data: bytes, addr: Any) -> None: ...

    def receive_all(self) -> List[Tuple[Any, bytes]]: ...


class UdpNonBlockingSocket:
    """Non-blocking UDP socket bound to a local port."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind((host, port))

    @classmethod
    def bind_to_port(cls, port: int) -> "UdpNonBlockingSocket":
        return cls(port)

    @property
    def local_addr(self) -> Tuple[str, int]:
        return self._sock.getsockname()

    def send_to(self, data: bytes, addr) -> None:
        try:
            self._sock.sendto(data, addr)
        except (BlockingIOError, OSError):
            pass  # non-blocking: drop on full buffer (UDP semantics)

    def receive_all(self) -> List[Tuple[Any, bytes]]:
        """Drain every pending datagram -> [(addr, bytes)]."""
        out = []
        while True:
            try:
                data, addr = self._sock.recvfrom(65536)
            except (BlockingIOError, OSError):
                break
            out.append((addr, data))
        return out

    def close(self) -> None:
        self._sock.close()


class _CorruptStream(Exception):
    """Framing desynchronized — the connection must be torn down."""


class _TcpConn:
    """One TCP connection: frame-aligned send queue + receive buffer.

    The send side queues COMPLETE frames and tracks how many bytes of the
    head frame went out (``sent0``), so a connection handoff can drop the
    partially-transmitted head instead of splicing a frame tail into a
    fresh stream (which would permanently misalign the receiver)."""

    __slots__ = ("sock", "rbuf", "frames", "sent0")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rbuf = bytearray()
        self.frames: deque = deque()  # complete framed byte strings
        self.sent0 = 0  # bytes of frames[0] already transmitted

    def queue(self, framed: bytes) -> None:
        self.frames.append(framed)

    def flush(self) -> bool:
        """Send as much as possible; False if the connection died."""
        while self.frames:
            head = self.frames[0]
            try:
                sent = self.sock.send(
                    head[self.sent0:] if self.sent0 else head
                )
            except (BlockingIOError, InterruptedError):
                return True
            except OSError:
                return False
            self.sent0 += sent
            if self.sent0 < len(head):
                return True
            self.frames.popleft()
            self.sent0 = 0
        return True

    def adopt_queue_from(self, other: "_TcpConn") -> None:
        """Carry over pending frames, dropping a partially-sent head (its
        tail belongs to the dying stream; the datagram is lost — UDP-like)."""
        frames = other.frames
        if other.sent0 and frames:
            frames.popleft()
        self.frames.extend(frames)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class TcpNonBlockingSocket:
    """Second production transport: framed datagrams over non-blocking TCP.

    The reference's drop-in transport alternative is matchbox WebRTC for
    environments where raw UDP is unavailable (/root/reference/README.md:79);
    the equivalent niche here is TCP — NAT/firewall-friendly, tunnels over
    SSH/TLS proxies.  Same two-method protocol as UDP, so sessions take it
    unchanged: datagrams are type-tagged, length-prefixed frames on the
    stream; peer addressing stays (host, port) — the LISTENING address of
    each peer, so either side may dial and both directions share one
    connection (the connection initiated by the lower listen address wins a
    simultaneous dial, on both sides).

    Semantics notes: TCP delivers reliably/in-order, which the GGRS protocol
    tolerates (it is loss-tolerant, not loss-requiring); head-of-line
    blocking makes it a worse *competitive* transport than UDP — same
    trade-off the reference accepts for WebRTC data channels in reliable
    mode.

    Peer identity: an inbound connection is keyed by the IP observed on the
    wire (``getpeername``) + the listener port announced in the peer's hello
    frame, so NATed dialers are keyed by their routable return address (the
    one this side's address book dials), not their self-reported private IP.
    Caveat for multi-homed hosts: if the peer's return route uses a
    different interface than the address you dial it at, the keys can still
    disagree — bind each listener to a specific interface (not 0.0.0.0) in
    multi-homed deployments so the simultaneous-dial tie-break is computed
    on the same key by both sides."""

    _MAX_FRAME = 1 << 20
    _DATA = 0x00
    _HELLO = 0x01  # payload = 4-byte IP + 2-byte port of the sender's listener

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self._listener.setblocking(False)
        self._conns: dict = {}  # peer listen-addr -> _TcpConn
        self._pending: List[_TcpConn] = []  # accepted, hello not yet seen

    @classmethod
    def bind_to_port(cls, port: int) -> "TcpNonBlockingSocket":
        return cls(port)

    @property
    def local_addr(self) -> Tuple[str, int]:
        return self._listener.getsockname()

    # -- connection management (all non-blocking) --------------------------

    def _dial(self, addr) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        try:
            s.connect(addr)
        except (BlockingIOError, OSError):
            pass  # in progress (EINPROGRESS) or refused; writes will fail
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _TcpConn(s)
        # announce OUR listen address so the acceptor can key this conn.
        # The IP is this socket's own source address toward the peer (chosen
        # by the kernel at connect time) — a listener bound to 0.0.0.0 has
        # no single IP, but the route to this peer does.
        src_ip = "127.0.0.1"
        try:
            got = s.getsockname()[0]
            if got not in ("0.0.0.0", ""):
                src_ip = got
        except OSError:
            pass
        me = self.local_addr
        ip = me[0] if me[0] != "0.0.0.0" else src_ip
        hello = socket.inet_aton(ip) + me[1].to_bytes(2, "big")
        conn.queue(self._frame(hello, self._HELLO))
        self._conns[tuple(addr)] = conn

    @classmethod
    def _frame(cls, data: bytes, ftype: int = 0x00) -> bytes:
        if len(data) + 1 > cls._MAX_FRAME:
            raise ValueError(
                f"datagram of {len(data)} bytes exceeds the transport's "
                f"{cls._MAX_FRAME - 1}-byte frame limit"
            )
        return (len(data) + 1).to_bytes(4, "big") + bytes([ftype]) + data

    @staticmethod
    def _pump(conn: _TcpConn) -> bool:
        """Read available bytes into the conn's rbuf; False if peer closed."""
        while True:
            try:
                chunk = conn.sock.recv(65536)
            except (BlockingIOError, InterruptedError):
                return True
            except OSError:
                return False
            if not chunk:
                return False
            conn.rbuf.extend(chunk)

    def _pop_frames(self, rbuf: bytearray) -> List[Tuple[int, bytes]]:
        """-> [(frame_type, payload)] for every complete frame in rbuf.

        Raises :class:`_CorruptStream` on an impossible length prefix — the
        stream is misaligned and cannot recover; the caller tears the
        connection down (the next send re-dials)."""
        frames = []
        while len(rbuf) >= 4:
            n = int.from_bytes(rbuf[:4], "big")
            if n < 1 or n > self._MAX_FRAME:
                raise _CorruptStream()
            if len(rbuf) < 4 + n:
                break
            frames.append((rbuf[4], bytes(rbuf[5:4 + n])))
            del rbuf[:4 + n]
        return frames

    def _accept_all(self) -> None:
        while True:
            try:
                s, _ = self._listener.accept()
            except (BlockingIOError, OSError):
                break
            s.setblocking(False)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._pending.append(_TcpConn(s))

    # -- NonBlockingSocket protocol ----------------------------------------

    def send_to(self, data: bytes, addr) -> None:
        """Queue one datagram to the peer listening at ``addr`` (dials on
        first use; drops the connection on a dead socket so the next send
        re-dials — UDP-like fire-and-forget at the datagram layer)."""
        addr = tuple(addr)
        if addr not in self._conns:
            self._dial(addr)
        conn = self._conns[addr]
        conn.queue(self._frame(data))
        if not conn.flush():
            # connection dead; drop it so the next send re-dials (UDP-like
            # fire-and-forget semantics at the datagram layer)
            conn.close()
            del self._conns[addr]

    def receive_all(self) -> List[Tuple[Any, bytes]]:
        """Drain every complete datagram -> [(peer_listen_addr, bytes)];
        also accepts/promotes inbound connections and flushes send backlogs."""
        self._accept_all()
        out: List[Tuple[Any, bytes]] = []
        # promote pending accepted conns once their hello frame arrives
        still_pending: List[_TcpConn] = []
        me = self.local_addr
        my_key = ("127.0.0.1" if me[0] == "0.0.0.0" else me[0], me[1])
        for conn in self._pending:
            alive = self._pump(conn)
            try:
                frames = self._pop_frames(conn.rbuf)
            except _CorruptStream:
                conn.close()
                continue
            if not frames:
                if alive:
                    still_pending.append(conn)  # hello not complete yet
                else:
                    conn.close()
                continue
            ftype, payload = frames[0]
            if ftype != self._HELLO or len(payload) != 6:
                conn.close()  # protocol violation: first frame must be hello
                continue
            # Key the conn by the peer IP OBSERVED on the wire (getpeername)
            # plus the hello's listener port.  The self-reported hello IP is
            # the kernel-chosen source IP of the dialer's socket, which on
            # NATed hosts is a private address the acceptor cannot dial —
            # the observed address is the routable return path and matches
            # the address book the session dials.  Self-report is only the
            # fallback when the socket cannot name its peer.
            hello_ip = socket.inet_ntoa(payload[:4])
            try:
                observed_ip = conn.sock.getpeername()[0]
            except OSError:
                observed_ip = hello_ip
            if observed_ip in ("", "0.0.0.0"):
                observed_ip = hello_ip
            peer = (observed_ip, int.from_bytes(payload[4:6], "big"))
            data = [p for t, p in frames[1:] if t == self._DATA]
            if peer in self._conns:
                # simultaneous dial: the connection initiated by the LOWER
                # listen address is canonical on both sides
                if my_key < peer:
                    # our own dialed conn wins; drain then drop the inbound
                    out.extend((peer, p) for p in data)
                    conn.close()
                    continue
                old = self._conns[peer]
                conn.adopt_queue_from(old)
                old.close()
                self._conns[peer] = conn
            else:
                self._conns[peer] = conn
            out.extend((peer, p) for p in data)
        self._pending = still_pending
        # established connections: flush backlog, then read
        for addr in list(self._conns):
            conn = self._conns[addr]
            if not conn.flush():
                conn.close()
                del self._conns[addr]
                continue
            alive = self._pump(conn)
            try:
                frames = self._pop_frames(conn.rbuf)
            except _CorruptStream:
                conn.close()
                del self._conns[addr]
                continue
            for ftype, payload in frames:
                if ftype == self._DATA:
                    out.append((addr, payload))
                # helloes on established conns are idempotent re-keys: ignore
            if not alive:
                conn.close()
                del self._conns[addr]
        return out

    def close(self) -> None:
        """Close the listener and every connection."""
        for conn in self._conns.values():
            conn.close()
        for conn in self._pending:
            conn.close()
        self._listener.close()
