"""Session-facing enums, events, and errors — the GGRS surface the driver and
user code consume (reconstructed API per SURVEY.md §2.3; citations inline)."""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, List, Optional


class InputStatus(enum.IntEnum):
    """Per-player input status delivered with PlayerInputs
    (/root/reference/src/lib.rs:92-94)."""

    CONFIRMED = 0
    PREDICTED = 1
    DISCONNECTED = 2


class SessionState(enum.Enum):
    """P2P/Spectator lifecycle (`current_state()`,
    /root/reference/src/schedule_systems.rs:140)."""

    SYNCHRONIZING = "synchronizing"
    RUNNING = "running"


class PlayerType(enum.Enum):
    """LOCAL / REMOTE / SPECTATOR (PlayerType analog)."""
    LOCAL = "local"
    REMOTE = "remote"
    SPECTATOR = "spectator"


@dataclass(frozen=True)
class Player:
    """One player slot: kind + handle (+ peer address for remote/spectator)."""
    kind: PlayerType
    handle: int
    address: Optional[Any] = None  # remote/spectator peer address


class DesyncDetection:
    """Desync-detection mode (`with_desync_detection_mode`, SURVEY §2.3)."""

    def __init__(self, interval: Optional[int] = None):
        self.interval = interval  # None = Off; n = compare every n frames

    OFF: "DesyncDetection"

    @staticmethod
    def on(interval: int) -> "DesyncDetection":
        return DesyncDetection(interval)

    @property
    def enabled(self) -> bool:
        return self.interval is not None


DesyncDetection.OFF = DesyncDetection(None)


# -- events (GgrsEvent<T>, consumed via session.events();
#    /root/reference/examples/box_game/box_game_p2p.rs:104-119) --------------


@dataclass(frozen=True)
class Synchronizing:
    """Sync handshake progress with a peer (count/total roundtrips)."""
    addr: Any
    total: int
    count: int


@dataclass(frozen=True)
class Synchronized:
    """Peer completed the sync handshake."""
    addr: Any


@dataclass(frozen=True)
class Disconnected:
    """Peer exceeded the disconnect timeout."""
    addr: Any


@dataclass(frozen=True)
class NetworkInterrupted:
    """Peer quiet past the notify threshold (may still resume)."""
    addr: Any
    disconnect_timeout_ms: int


@dataclass(frozen=True)
class NetworkResumed:
    """Interrupted peer spoke again."""
    addr: Any


@dataclass(frozen=True)
class DesyncDetected:
    """A confirmed frame's checksum differs from a peer's."""
    frame: int
    local_checksum: int
    remote_checksum: int
    addr: Any


# -- errors (GgrsError) ------------------------------------------------------


class GgrsError(Exception):
    """Base class of session errors (GgrsError analog)."""
    pass


class PredictionThresholdError(GgrsError):
    """Too far ahead of remote inputs — the driver logs and skips the frame
    (/root/reference/src/schedule_systems.rs:162-164)."""


class MismatchedChecksumError(GgrsError):
    """SyncTest resimulation produced a different checksum
    (/root/reference/src/schedule_systems.rs:106-115)."""

    def __init__(self, current_frame: int, mismatched_frames: List[int]):
        self.current_frame = current_frame
        self.mismatched_frames = mismatched_frames
        super().__init__(
            f"checksum mismatch at frames {mismatched_frames} "
            f"(current frame {current_frame})"
        )


class NotSynchronizedError(GgrsError):
    """Session is still synchronizing with remotes."""


class InvalidRequestError(GgrsError):
    """Misuse of the session API (bad handle, missing input, ...)."""


@dataclass
class NetworkStats:
    """`network_stats(handle)` surface
    (/root/reference/examples/box_game/box_game_p2p.rs:121-142).

    ``is_live`` is False for handles with no live endpoint behind them —
    local handles, disconnected peers, spectators.  Those return a zeroed
    snapshot instead of raising, so samplers can walk every handle without
    try/except churn (the :class:`~bevy_ggrs_tpu.telemetry.netstats.
    NetStatsSampler` skips non-live snapshots silently)."""

    ping_ms: float = 0.0
    send_queue_len: int = 0
    kbps_sent: float = 0.0
    local_frames_behind: int = 0
    remote_frames_behind: int = 0
    is_live: bool = True
