"""BatchedRunner — the many-worlds game server driver.

The reference runs ONE session per process (`Session` is a singleton Bevy
resource, /root/reference/src/lib.rs:79-88); a server hosting M lobbies runs
M processes, each dispatching its own tiny sim.  A TPU inverts the economics:
one chip eats hundreds of small worlds per pass, and on remote-attached
devices the per-dispatch submission cost dominates small worlds — so M
serial dispatches are the one thing the server must not do.

This driver owns M sessions (any mix of SyncTest / P2P / in-process — they
only need the GgrsRequest protocol) over ONE resident ``[M, ...]`` stacked
world.  Each server tick it:

1. polls every session and collects its request list (host-side, cheap);
2. splits each lobby's list into an ordered sequence of ops —
   ``Load(frame)`` / ``Run([Save|Advance ...])`` — exactly the segments
   GgrsRunner fuses per lobby (runner.py _handle_requests);
3. executes ops positionally as WAVES across lobbies: wave w batches every
   lobby's w-th Run into ONE dispatch through the shape-bucketed executor
   (ops/batch.BucketedWaveExecutor: smallest power-of-two depth bucket
   covering the wave's ``k_hot``, exact unmasked program for full waves,
   ``n_real``-masked program for ragged ones), and serves Load ops from
   per-lobby snapshot rings via ONE fused mixed-source gather — lobbies
   loading rows of *different* past stacked buffers are grouped per buffer
   (snapshot/lazy.plan_row_gather) and scattered into the resident world in
   a single jitted program.

The steady-state tick therefore costs a CONSTANT number of device
dispatches independent of the lobby count M (one per load wave, one per
run wave, plus one fused ``store_state`` dispatch for non-identity
strategies) — verified by the dispatch-flatness gate in bench.py's batched
stage.  Inputs stage through persistent preallocated host buffers (no
per-tick allocation), and saves store ``LazySlice(stacked, (lobby,
frame_idx))`` handles — one ``[M, K, ...]`` buffer per wave backs every
lobby's ring rows, with checksum pulls riding the process-wide BatchChecks
fusion (snapshot/lazy.py).

Bit-equality caveat (same as ops/batch.py): the vmapped program is a
DIFFERENT XLA program than the single-lobby one, so for variant-unstable
float sims a batched lobby is not guaranteed bit-identical to a solo run of
the same inputs; integer/fixed-point sims and variant-stable steps (probe
with ops/variant_probe.py) batch exactly — proven by
tests/test_batched_runner.py against M independent GgrsRunners.  Canonical
modes are refused for the same reason (make_batched_resim_fn docstring).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from . import telemetry
from .app import App
from .ops.batch import (
    BucketedWaveExecutor,
    DraftWaveScheduler,
    ShardedWaveExecutor,
    stack_worlds,
)
from .ops.speculation import SpeculationCache, SpeculationConfig
from .session.events import (
    DesyncDetected,
    InputStatus,
    MismatchedChecksumError,
    NotSynchronizedError,
    PredictionThresholdError,
    SessionState,
)
from .session.requests import AdvanceRequest, GgrsRequest, LoadRequest, SaveRequest
from .session.synctest import SyncTestSession
from .snapshot.lazy import (
    BatchChecks,
    LazySlice,
    fused_gather_rows,
    fused_load_rows,
    materialize,
    plan_row_gather,
    readback_queue,
)
from .snapshot.ring import SnapshotRing, rollback_many
from .utils import compile_guard
from .utils.frames import NULL_FRAME, frame_add
from .utils.tracing import span


class _Op:
    __slots__ = ("load_frame", "load_cause", "run")

    def __init__(self, load_frame=None, run=None, load_cause=None):
        self.load_frame = load_frame  # int | None
        self.load_cause = load_cause  # RollbackCause | None
        self.run = run  # List[GgrsRequest] | None


def _split_ops(requests: List[GgrsRequest]) -> List[_Op]:
    """[Load?](Advance|Save)* request list -> ordered Load/Run ops
    (the same maximal-run fusion as GgrsRunner._handle_requests)."""
    ops: List[_Op] = []
    i, n = 0, len(requests)
    while i < n:
        r = requests[i]
        if isinstance(r, LoadRequest):
            ops.append(_Op(load_frame=r.frame, load_cause=r.cause))
            i += 1
        else:
            j = i
            while j < n and isinstance(requests[j], (AdvanceRequest, SaveRequest)):
                j += 1
            ops.append(_Op(run=requests[i:j]))
            i = j
    return ops


class ShardPlanner:
    """Host-side shard accounting for the lobby-sharded executor.

    Lobby lanes map to devices STATICALLY — lobby ``b`` lives on device
    ``b // (m_pad / D)`` (contiguous blocks, the layout shard_map splits the
    stacked world into) — so the "packing" decision the planner owns is the
    per-tick bucket shape: it derives each device's active-lane count and
    hottest advance depth from the wave's ``ks``, publishes the tick's
    ``shard_imbalance_ratio`` gauge (max/mean active lobbies per device —
    1.0 is a perfectly flat wave), and tracks the worst ratio seen.  A
    ratio that stays high is the signal to re-home lobbies across devices
    (a roadmap item — re-homing moves resident state, so it must be rare
    and amortized, not per-tick)."""

    def __init__(self, n_lobbies: int, n_devices: int):
        self.n_lobbies = int(n_lobbies)
        self.n_devices = int(n_devices)
        self.m_pad = -(-self.n_lobbies // self.n_devices) * self.n_devices
        self.lanes_per_shard = self.m_pad // self.n_devices
        self.last_imbalance = 1.0
        self.max_imbalance = 1.0
        self.waves_planned = 0
        self._g_imbalance = telemetry.registry().bind_gauge(
            "shard_imbalance_ratio",
            "max/mean active lobbies per device for the tick's run wave",
        )

    def shard_of(self, b: int) -> int:
        """Device index owning lobby lane ``b``."""
        return b // self.lanes_per_shard

    def plan(self, ks: Sequence[int]) -> dict:
        """Pack one wave's per-lobby advance counts into per-device
        buckets; returns ``{"active_per_shard", "k_hot_per_shard",
        "imbalance_ratio"}`` and publishes the gauge."""
        active = [0] * self.n_devices
        hot = [0] * self.n_devices
        for b, k in enumerate(ks):
            if k > 0:
                d = self.shard_of(b)
                active[d] += 1
                hot[d] = max(hot[d], k)
        total = sum(active)
        ratio = (max(active) * self.n_devices / total) if total else 1.0
        self.last_imbalance = ratio
        self.max_imbalance = max(self.max_imbalance, ratio)
        self.waves_planned += 1
        self._g_imbalance.set(ratio)
        return {
            "active_per_shard": active,
            "k_hot_per_shard": hot,
            "imbalance_ratio": ratio,
        }


class BatchedRunner:
    """M lobbies, one fused device dispatch per wave (module docstring).

    With ``mesh=`` (a ``parallel.make_lobby_mesh()`` handle) the lobby axis
    additionally shards across the mesh's devices: the resident stacked
    world is padded to a device-count multiple, placed with lobby-axis
    sharding, and every wave dispatches through the
    :class:`~.ops.batch.ShardedWaveExecutor` — O(1) dispatches PER DEVICE
    per tick.  Falls back to the single-device executor automatically when
    the mesh has one device or the process sees only one device."""

    def __init__(
        self,
        app: App,
        sessions: Sequence,
        read_inputs: Optional[Callable[[int, List[int]], Dict[int, np.ndarray]]] = None,
        on_mismatch: Optional[Callable[[int, MismatchedChecksumError], None]] = None,
        on_event: Optional[Callable[[int, object], None]] = None,
        k_max: Optional[int] = None,
        pipeline: bool = True,
        packed: bool = True,
        mesh=None,
        speculation: Optional[SpeculationConfig] = None,
    ):
        if app.canonical_depth is not None or app.canonical_branches is not None:
            raise ValueError(
                "BatchedRunner is incompatible with canonical mode "
                "(see ops/batch.make_batched_resim_fn)"
            )
        self.app = app
        self.sessions = list(sessions)
        m = len(self.sessions)
        if m == 0:
            raise ValueError("BatchedRunner needs at least one session")
        # the deepest run any session can emit in one tick: a rollback spans
        # the full window plus the live advance
        windows = []
        for s in self.sessions:
            w = (
                s.rollback_window()
                if hasattr(s, "rollback_window")
                else s.max_prediction()
            )
            windows.append(max(w, s.max_prediction()))
            if self.app.retention < w:
                raise ValueError(
                    f"App(retention={self.app.retention}) < session rollback "
                    f"window ({w}) — see GgrsRunner.set_session"
                )
        self.k_max = k_max if k_max is not None else max(windows) + 1
        self.read_inputs = read_inputs or (
            lambda lobby, handles: {h: app.zero_inputs()[h] for h in handles}
        )
        self.on_mismatch = on_mismatch
        self.on_event = on_event
        # lobby-mesh sharding: only engage when the mesh actually spans
        # multiple devices AND the process can see them (single-device
        # fallback keeps laptops/1-chip hosts on the proven path)
        self.mesh = None
        self.planner: Optional[ShardPlanner] = None
        if mesh is not None and int(mesh.devices.size) > 1:
            import jax as _jx

            if len(_jx.devices()) > 1:
                self.mesh = mesh
        if self.mesh is not None:
            self.planner = ShardPlanner(m, int(self.mesh.devices.size))
            m_pad = self.planner.m_pad
        else:
            m_pad = m
        self._m_pad = m_pad
        # resident world: padded to a device-count multiple in sharded mode
        # (pad lanes are permanently idle — every wave masks them at
        # n_real=0, no session ever maps to them) and placed with lobby-axis
        # sharding so each device owns its contiguous block of lanes
        self.worlds = stack_worlds([app.init_state() for _ in range(m_pad)])
        # shape-bucketed wave programs replace the single k_max-deep padded
        # fn: a 1-advance lockstep wave dispatches the exact k=1 program, a
        # ragged rollback wave the smallest masked bucket covering it.
        # recycle_outputs stays OFF here — the rings below hold LazySlice
        # handles into past stacked outputs, so they must never be donated.
        if self.mesh is not None:
            from .parallel.mesh import shard_lobby_worlds

            self.worlds = shard_lobby_worlds(self.mesh, self.worlds)
            self.exec = ShardedWaveExecutor(app, self.k_max, self.mesh)
        else:
            self.exec = BucketedWaveExecutor(app, self.k_max)
        # per-lobby live-world checksum handles (ONE vmapped dispatch for
        # all M rows; leading saves reuse these instead of dispatching)
        import jax as _jax

        from .snapshot.checksum import world_checksum as _wc

        self._batch_checksum_fn = _jax.jit(
            lambda ws: _jax.vmap(lambda w: _wc(app.reg, w))(ws)
        )
        # pipelined readback: start non-blocking device->host checksum
        # copies at dispatch time and collect them at the next tick() —
        # same engine as GgrsRunner (docs/architecture.md "Tick pipeline")
        self.pipeline = bool(pipeline)
        self._rbq = readback_queue()
        init_batch = BatchChecks(self._batch_checksum_fn(self.worlds))
        if self.pipeline:
            self._rbq.start(init_batch)
        self._world_checksum = [init_batch.ref(b) for b in range(m)]
        # device-memory accounting (telemetry/devmem.py): the resident
        # stacked world, the per-lobby snapshot rings (one padded-world
        # footprint per stored entry) and the staging buffers below all
        # report under this instance's namespace and die with it
        import weakref

        from .utils.mem import tree_device_bytes

        self._devmem_tag = telemetry.devmem.scope("batched")
        weakref.finalize(self, telemetry.devmem.forget_scope, self._devmem_tag)
        worlds_nbytes = tree_device_bytes(self.worlds)
        telemetry.devmem.note(self._devmem_tag + "/worlds", worlds_nbytes)
        row_nbytes = worlds_nbytes // max(m_pad, 1)
        self.rings = [SnapshotRing(depth=max(windows) + 2) for _ in range(m)]
        for b, ring in enumerate(self.rings):
            ring.set_accounting(
                f"{self._devmem_tag}/ring{b}", row_nbytes
            )
        self.frames = [0] * m  # per-lobby RollbackFrameCount
        self.confirmed = [NULL_FRAME] * m
        self.ticks = 0
        self.rollbacks = 0
        self.device_dispatches = 0
        self.fused_loads = 0
        self.fallback_loads = 0
        self.stalled = [0] * m
        self._np = self.sessions[0].num_players()
        for s in self.sessions:
            if s.num_players() != self._np:
                raise ValueError("all lobbies must share num_players "
                                 "(one batched input tensor)")
        # persistent staging: the per-tick input/status tensors are filled in
        # place every wave instead of re-allocated (allocation churn was a
        # measurable slice of the 1-CPU-host tick).  Idle/padded lanes keep
        # stale rows — the padded program's n_real mask discards them, the
        # exact program never sees them.
        self._stage_inputs = np.zeros(
            (m_pad, self.k_max, self._np, *app.input_shape), app.input_dtype
        )
        self._stage_status = np.zeros((m_pad, self.k_max, self._np), np.int8)
        self._stage_starts = np.zeros((m_pad,), np.int32)
        # packed single-upload staging (ops/packing.py): the wave's inputs,
        # status, per-lobby start frames AND per-lobby n_real ride ONE
        # persistent int8 buffer — a wave costs one host->device upload
        # instead of 3-4.  Pad lanes (sharded mode) are zeroed here and
        # never written, so their prefix reads n_real=0 forever; idle REAL
        # lanes get their prefix rewritten every wave (a stale nonzero
        # n_real from a previous wave would resurrect dead advances).
        self.packed = bool(packed)
        self._stage_packed = (
            app.packed_spec.new_batch_buffer(m_pad, self.k_max)
            if self.packed else None
        )
        telemetry.devmem.note(
            self._devmem_tag + "/staging",
            self._stage_inputs.nbytes + self._stage_status.nbytes
            + self._stage_starts.nbytes,
        )
        if self._stage_packed is not None:
            telemetry.devmem.note(
                self._devmem_tag + "/packed_staging",
                self._stage_packed.nbytes,
            )
        # Speculative draft waves (docs/architecture.md "Speculative rollback
        # servicing"): per-lobby branch caches filled by an EXTRA wave that
        # only occupies lanes the active bucket left idle; on a LoadRequest
        # whose corrected run was fully hedged the rollback is served as a
        # row scatter of the cached final plus LazySlice ring pushes —
        # zero resim frames.  The mode matrix is strict (ValueError, never a
        # silent fallback): drafts ride the packed batch staging, cached
        # branch states scatter STRAIGHT into the resident world so the
        # snapshot strategy must be identity, and the draft gather/scatter
        # is not yet shard-aware.
        self.spec_caches: Optional[List[SpeculationCache]] = None
        self.spec_config = speculation
        self.draft_waves = 0
        self.cache_served_frames = 0
        self._last_wave = None  # (prev_worlds, stacked, ks) of last run wave
        self._last_adv: Optional[List[list]] = None
        self._draft_sched: Optional[DraftWaveScheduler] = None
        self._stage_packed_draft = None
        if speculation is not None:
            if not self.packed:
                raise ValueError(
                    "BatchedRunner speculation requires packed=True: draft "
                    "waves ride the packed single-upload batch staging "
                    "(mode matrix in docs/architecture.md)"
                )
            if not self.app.reg.is_identity_strategy():
                raise ValueError(
                    "BatchedRunner speculation requires an identity snapshot "
                    "strategy: cached branch states scatter straight into "
                    "the resident stacked world on a hit (mode matrix in "
                    "docs/architecture.md)"
                )
            if self.mesh is not None:
                raise ValueError(
                    "BatchedRunner speculation is not shard-aware yet: the "
                    "draft wave's base gather and hit scatter assume a "
                    "single-device resident world (mode matrix in "
                    "docs/architecture.md)"
                )
            depth = max(speculation.depth, 1)
            if depth > self.k_max:
                raise ValueError(
                    f"speculation depth {depth} exceeds k_max={self.k_max}; "
                    "drafts dispatch through the same bucketed wave "
                    "executor as real runs"
                )
            self.spec_caches = [
                SpeculationCache(app, speculation) for _ in range(m)
            ]
            self._draft_sched = DraftWaveScheduler(m_pad)
            self._draft_bucket = self.exec.bucket_for(depth)
            self._stage_packed_draft = app.packed_spec.new_batch_buffer(
                m_pad, self._draft_bucket
            )
            telemetry.devmem.note(
                self._devmem_tag + "/draft_staging",
                self._stage_packed_draft.nbytes,
            )
            self._m_drafts = telemetry.registry().bind_counter(
                "draft_dispatches_total",
                "speculative draft dispatches issued into idle pipeline "
                "slots / spare wave lanes",
            )
        # stable bound-method refs: snapshot-strategy hooks fused into the
        # batched load/save programs (and the jit-cache keys of
        # fused_load_rows / fused_gather_rows)
        if self.app.reg.is_identity_strategy():
            self._load_transform = None
            self._store_transform = None
        else:
            self._load_transform = self.app.reg.load_state
            self._store_transform = self.app.reg.store_state
        # tick-phase latency attribution (flight recorder + tick_phase_ms
        # histograms — docs/observability.md "Phase timers")
        self._phases = telemetry.PhaseSet(owner="batched")
        # pre-bound argument-free counters: name+help registered ONCE here,
        # per-tick increments are attribute checks (not dict/string traffic)
        _treg = telemetry.registry()
        self._m_ticks = _treg.bind_counter(
            "server_ticks_total", "batched-server ticks (all lobbies)"
        )
        self._m_dispatches = _treg.bind_counter(
            "device_dispatches_total",
            "fused device dispatches (resim + load + store waves)",
        )
        self._m_resim_frames = _treg.bind_counter(
            "resim_frames_total",
            "frames resimulated beyond the first of each dispatch",
        )
        self._m_rollbacks = _treg.bind_counter(
            "rollbacks_total", "LoadRequests executed"
        )
        self._m_fused_loads = _treg.bind_counter(
            "fused_load_dispatches_total",
            "load waves served by one mixed-source gather",
        )
        self._m_fallback_loads = _treg.bind_counter(
            "fallback_load_rows_total",
            "load rows served by per-lobby scatter (non-LazySlice snapshot)",
        )

    # -- per-tick driver ----------------------------------------------------

    def tick(self) -> None:
        """One server tick: poll + step every lobby, flush as waves."""
        self.ticks += 1
        self._m_ticks.inc()
        ph = self._phases
        ph.begin_tick()
        if self.pipeline:
            # harvest last tick's landed checksum copies before the lobby
            # polls publish them (never blocks)
            with ph.phase("readback_harvest"):
                self._rbq.harvest()
        per_lobby_ops: List[List[_Op]] = []
        for b, s in enumerate(self.sessions):
            per_lobby_ops.append(self._collect_ops(b, s))
        n_waves = max((len(ops) for ops in per_lobby_ops), default=0)
        self._last_wave = None
        self._last_adv = None
        for w in range(n_waves):
            wave_ops = [
                ops[w] if w < len(ops) else None for ops in per_lobby_ops
            ]
            self._do_loads(wave_ops, per_lobby_ops, w)
            self._do_runs(wave_ops)
        if self.spec_caches is not None:
            # hedge the tick's predicted transitions into the lanes the last
            # run wave left idle (draft capacity, not extra census)
            self._speculate_idle_lanes()
        for b, s in enumerate(self.sessions):
            cf = s.confirmed_frame()
            self.confirmed[b] = cf
            self.rings[b].confirm(cf)
        if n_waves:
            # handshake-only ticks (no lobby emitted an op) stay out of the
            # flight ring — they would evict the interesting entries.  The
            # residency stamp feeds the trace counter track; gated so the
            # fully-disabled path computes nothing (telemetry/trace.py)
            if ph.on:
                ph.end_tick(
                    frame=max(self.frames), lobbies=len(self.sessions),
                    device_bytes=telemetry.devmem.total(),
                    pipeline_depth=(
                        self._rbq.depth() if self.pipeline else 0
                    ),
                )
            else:
                ph.end_tick(
                    frame=max(self.frames), lobbies=len(self.sessions)
                )

    def _collect_ops(self, b: int, s) -> List[_Op]:
        with self._phases.phase("net_poll"):
            if hasattr(s, "poll_remote_clients"):
                s.poll_remote_clients()
            if hasattr(s, "events") and (
                self.on_event is not None or telemetry.enabled()
            ):
                for ev in s.events():
                    if isinstance(ev, DesyncDetected):
                        telemetry.record(
                            "checksum_mismatch", source="p2p", lobby=b,
                            frames=[ev.frame], local_checksum=ev.local_checksum,
                            remote_checksum=ev.remote_checksum,
                            addr=repr(ev.addr),
                        )
                        if telemetry.forensics_dir() is not None:
                            # lobby_world is a device gather — only pay it
                            # when a report will actually be written
                            telemetry.write_desync_report(
                                "p2p_desync", reg=self.app.reg,
                                world=self.lobby_world(b), frames=[ev.frame],
                                local_checksum=ev.local_checksum,
                                remote_checksum=ev.remote_checksum,
                                addr=ev.addr, lobby=b,
                            )
                    if self.on_event is not None:
                        self.on_event(b, ev)
        if isinstance(s, SyncTestSession):
            handles = list(range(s.num_players()))
        else:
            if s.current_state() != SessionState.RUNNING:
                return []  # still handshaking: poll only
            handles = list(s.local_player_handles())
        for h, v in self.read_inputs(b, handles).items():
            s.add_local_input(h, v)
        try:
            with self._phases.phase("session_step"), span("SessionAdvanceFrame"):
                requests = s.advance_frame()
        except MismatchedChecksumError as e:
            self._report_mismatch(b, e)
            if self.on_mismatch is not None:
                self.on_mismatch(b, e)
                return []
            raise
        except PredictionThresholdError:
            self.stalled[b] += 1
            telemetry.count(
                "stalled_frames_total", help="ticks skipped on stall",
                kind="p2p", lobby=b,
            )  # cold path (exceptional), help re-pass is fine here
            telemetry.record("stall", lobby=b, frame=self.frames[b],
                             reason="prediction_threshold")
            return []
        except NotSynchronizedError:
            return []
        return _split_ops(requests)

    # -- loads --------------------------------------------------------------

    def _do_loads(
        self,
        wave_ops: List[Optional[_Op]],
        per_lobby_ops: Optional[List[List[_Op]]] = None,
        w: int = 0,
    ) -> None:
        loads = [
            (b, op.load_frame, op.load_cause)
            for b, op in enumerate(wave_ops)
            if op is not None and op.load_frame is not None
        ]
        if not loads:
            return
        self.rollbacks += len(loads)
        for b, f, _c in loads:
            self._phases.note_rollback(self.frames[b] - f)
        if telemetry.enabled():
            for b, f, cause in loads:
                depth = self.frames[b] - f
                # cause-less loads (legacy session types) blame "unknown" so
                # rollback_cause_total summed over handles still equals
                # rollbacks_total across every driver
                blamed = cause.handle if cause is not None else "unknown"
                if blamed is None:
                    blamed = "unknown"
                lateness = cause.lateness if cause is not None else depth
                telemetry.count("rollbacks_total", lobby=b)
                telemetry.count(
                    "rollback_cause_total",
                    help="rollbacks attributed to the peer whose input "
                         "caused them",
                    lobby=b, handle=blamed,
                )
                telemetry.observe(
                    "rollback_depth", depth, lobby=b,
                )
                telemetry.observe(
                    "input_lateness_frames", lateness,
                    "frames late the blamed input arrived",
                    lobby=b, handle=blamed,
                )
                telemetry.record(
                    "rollback", lobby=b, to_frame=f,
                    from_frame=self.frames[b], depth=depth,
                    handle=blamed, lateness=lateness,
                    cause_kind=cause.kind if cause is not None else "unknown",
                )
        # Speculation hit servicing: a Load whose FOLLOWING run (the next
        # wave's op for that lobby) was fully hedged is served entirely from
        # the lobby's branch cache — the ring pop is bookkeeping, the world
        # restore is one row scatter of the cached final, the run's saves
        # become LazySlice handles into the branch stack, and the consumed
        # run op is blanked so the next wave never dispatches it.  Partial
        # hits (corrected inputs hedged for a prefix only) fall through to
        # the miss path: serving them would split one run op across cache
        # and wave, shifting every other lobby's wave alignment.
        hits: Dict[int, tuple] = {}
        if self.spec_caches is not None and per_lobby_ops is not None:
            for b, f, _c in loads:
                ops_b = per_lobby_ops[b]
                nxt = ops_b[w + 1] if w + 1 < len(ops_b) else None
                if nxt is None or not nxt.run:
                    continue
                advs = [r for r in nxt.run if isinstance(r, AdvanceRequest)]
                if not advs:
                    continue
                got = self.spec_caches[b].lookup_seq(
                    f, np.stack([a.inputs for a in advs])
                )
                full = got is not None and got[0] == len(advs)
                telemetry.count(
                    "speculation_hits_total" if full
                    else "speculation_misses_total",
                    help="speculative branch-cache lookups",
                )
                if full:
                    hits[b] = (f, got, nxt)
        if hits:
            t_hit = time.perf_counter()
            with self._phases.phase("rollback_load"), span("LoadWorldBatched"):
                for b, (f, got, nxt) in hits.items():
                    d, states_fn, checks_b = got
                    # bookkeeping-only rollback: pop the newer ring entries,
                    # keep the target's stored handle for leading saves
                    stored, cs0 = self.rings[b].rollback(f)
                    self.spec_caches[b].invalidate_after(f)
                    cbc = BatchChecks(checks_b)
                    self.worlds = _set_row(self.worlds, b, states_fn(d - 1))
                    self.device_dispatches += 1
                    self._m_dispatches.inc()
                    if self.pipeline:
                        self._rbq.start(cbc)
                    self._world_checksum[b] = cbc.ref(d - 1)
                    self.frames[b] = frame_add(f, d)
                    self.cache_served_frames += d
                    c = 0
                    for r in nxt.run:
                        if isinstance(r, AdvanceRequest):
                            c += 1
                        elif c == 0:
                            self.rings[b].push(r.frame, (stored, cs0))
                            r.cell.save(r.frame, cs0)
                        else:
                            cs = cbc.ref(c - 1)
                            self.rings[b].push(
                                r.frame,
                                (LazySlice(states_fn.stacked, c - 1), cs),
                            )
                            r.cell.save(r.frame, cs)
                    per_lobby_ops[b][w + 1] = None  # run consumed
                    telemetry.record(
                        "speculation_hit", lobby=b, frame=f, depth=d,
                        advances=d,
                    )
            telemetry.observe(
                "rollback_service_ms", (time.perf_counter() - t_hit) * 1e3,
                "wall ms to service one rollback (LoadRequest + its "
                "following Advance/Save run)",
                buckets=telemetry.LATENCY_MS_BUCKETS,
                path="hit",
            )
            loads = [(b, f, c) for b, f, c in loads if b not in hits]
            if not loads:
                return
        t_miss = time.perf_counter()
        with self._phases.phase("rollback_load"), span("LoadWorldBatched"):
            # batched mixed-source load: roll every ring back, group the
            # stored LazySlice handles by backing stacked buffer, and serve
            # the whole wave — even when lobbies load from DIFFERENT past
            # dispatches' buffers — as ONE jitted gather+scatter.  A
            # non-identity strategy's load_state hook is vmapped into the
            # same program.
            entries = rollback_many(
                self.rings, [(b, f) for b, f, _c in loads]
            )
            groups, fallback = plan_row_gather(
                [(b, stored) for b, (stored, _cs) in entries]
            )
            if groups:
                self.worlds = fused_load_rows(
                    self.worlds, groups, self._load_transform
                )
                self.device_dispatches += 1
                self.fused_loads += 1
                self._m_dispatches.inc()
                self._m_fused_loads.inc()
            for b, stored in fallback:
                # rare path: a ring entry that is a concrete pytree (not a
                # LazySlice into a stacked buffer) — per-lobby scatter
                state = self.app.reg.load_state(materialize(stored))
                self.worlds = _set_row(self.worlds, b, state)
                self.device_dispatches += 1
                self.fallback_loads += 1
                self._m_dispatches.inc()
                self._m_fallback_loads.inc()
            for b, (_stored, cs) in entries:
                self._world_checksum[b] = cs
            for b, f, _c in loads:
                self.frames[b] = f
                if self.spec_caches is not None:
                    # branches hedged from now-superseded predicted states
                    # must not serve future lookups (SpeculationCache
                    # .invalidate_after)
                    self.spec_caches[b].invalidate_after(f)
        if self.spec_caches is not None:
            telemetry.observe(
                "rollback_service_ms", (time.perf_counter() - t_miss) * 1e3,
                "wall ms to service one rollback (LoadRequest + its "
                "following Advance/Save run)",
                buckets=telemetry.LATENCY_MS_BUCKETS,
                path="miss",
            )

    # -- runs ---------------------------------------------------------------

    def _do_runs(self, wave_ops: List[Optional[_Op]]) -> None:
        m = len(self.sessions)
        runs = [op.run if op is not None else None for op in wave_ops]
        adv = [
            [r for r in (run or []) if isinstance(r, AdvanceRequest)]
            for run in runs
        ]
        ks = [len(a) for a in adv]
        if not any(run for run in runs):
            return
        k_hot = max(ks)
        if k_hot > self.k_max:
            raise ValueError(
                f"lobby requested a {k_hot}-frame run > k_max={self.k_max}; "
                "raise BatchedRunner(k_max=...)"
            )
        identity = self.app.reg.is_identity_strategy()
        stacked = batch = None
        bucket = 0
        pre_checksum = list(self._world_checksum)
        prev_worlds = self.worlds
        ph = self._phases
        if k_hot > 0:
            ph.note_advances(sum(ks))
            bucket = self.exec.bucket_for(k_hot)
            # persistent staging fill (no per-tick allocation): write each
            # lobby's rows in place, repeat the last real row through the
            # bucket tail (padding inputs never affect results — masked by
            # n_real — but keeping them finite avoids garbage-driven traps)
            with ph.phase("stage_inputs"):
                if self.packed:
                    from .ops.packing import (
                        pack_prefix,
                        pack_row,
                        repeat_last_row,
                    )

                    pspec = self.app.packed_spec
                    packed = self._stage_packed
                    for b, a in enumerate(adv):
                        kb = len(a)
                        lane = packed[b]
                        # prefix rewritten EVERY wave: an idle lane must
                        # read n_real=0 even if a past wave left payload
                        pack_prefix(lane, self.frames[b], kb)
                        for i, x in enumerate(a):
                            pack_row(pspec, lane, i, x.inputs, x.status)
                        repeat_last_row(lane, kb, bucket)
                else:
                    inputs, status = self._stage_inputs, self._stage_status
                    starts = self._stage_starts
                    starts[:m] = self.frames  # pad lanes keep 0
                    for b, a in enumerate(adv):
                        kb = len(a)
                        if not kb:
                            continue
                        bi, bs = inputs[b], status[b]
                        for i, x in enumerate(a):
                            bi[i] = x.inputs
                            bs[i] = x.status
                        if kb < bucket:
                            bi[kb:bucket] = bi[kb - 1]
                            bs[kb:bucket] = bs[kb - 1]
            self.device_dispatches += 1
            self._m_dispatches.inc()
            self._m_resim_frames.inc(sum(max(k - 1, 0) for k in ks))
            telemetry.record(
                "dispatch", batched=True, k_hot=k_hot,
                active_lobbies=sum(1 for k in ks if k > 0),
            )
            # sharded mode: the planner packs the wave into per-device
            # buckets (gauge + imbalance tracking) and the executor sees
            # the full padded lane list so its M is device-divisible (the
            # resident world/staging are already padded — no per-wave
            # pad/trim dispatches on this path)
            wave_ks = ks
            if self.planner is not None:
                self.planner.plan(ks)
                wave_ks = ks + [0] * (self._m_pad - m)
            with ph.phase("wave_dispatch"), span("AdvanceWorldBatched"):
                if self.packed:
                    bucket, finals, stacked, checks_flat = (
                        self.exec.run_wave_packed(
                            self.worlds, self._stage_packed, wave_ks
                        )
                    )
                else:
                    bucket, finals, stacked, checks_flat = self.exec.run_wave(
                        self.worlds, inputs, status, starts, wave_ks
                    )
                batch = BatchChecks(checks_flat)
                if self.pipeline:
                    self._rbq.start(batch)
                self.worlds = finals
                for b in range(m):
                    if ks[b] > 0:
                        self.frames[b] = frame_add(self.frames[b], ks[b])
                        self._world_checksum[b] = batch.ref(
                            b * bucket + ks[b] - 1
                        )
            if self.spec_caches is not None:
                # draft-wave inputs (_speculate_idle_lanes): which lanes the
                # active bucket left idle, and each drafting lobby's base
                # state (the one feeding its LAST advance)
                self._last_wave = (prev_worlds, stacked, list(ks))
                self._last_adv = adv
        with ph.phase("store_save"), span("SaveWorldBatched"):
            # collect this wave's saves as (lobby, advance-count-before, req)
            saves = []
            for b, run in enumerate(runs):
                if not run:
                    continue
                c = 0
                for r in run:
                    if isinstance(r, AdvanceRequest):
                        c += 1
                    else:
                        saves.append((b, c, r))
            if not saves:
                return
            handles = []
            for b, c, _r in saves:
                if c == 0:
                    # pre-dispatch save: slice the PREVIOUS resident world's
                    # row (still alive in prev_worlds); its checksum handle
                    # was tracked, not recomputed
                    handles.append(LazySlice(prev_worlds, b))
                else:
                    handles.append(LazySlice(stacked, (b, c - 1)))
            if not identity:
                # one-dispatch non-identity saves: gather every saved row
                # (mixed prev_worlds / stacked sources) and vmap the
                # strategy's store_state over them in ONE jitted program;
                # ring entries become LazySlice handles into the fused
                # stored stack instead of M materialized pytrees
                groups, _none = plan_row_gather(list(enumerate(handles)))
                stored_stack = fused_gather_rows(groups, self._store_transform)
                order = np.concatenate([g[3] for g in groups])
                pos = np.empty_like(order)
                pos[order] = np.arange(len(order), dtype=order.dtype)
                handles = [
                    LazySlice(stored_stack, int(pos[j]))
                    for j in range(len(saves))
                ]
                self.device_dispatches += 1
                self._m_dispatches.inc()
            for (b, c, r), stored in zip(saves, handles):
                cs = (
                    pre_checksum[b] if c == 0
                    else batch.ref(b * bucket + (c - 1))
                )
                self.rings[b].push(r.frame, (stored, cs))
                # the ref itself is the provider (callable, with a
                # non-blocking peek() for the pipelined consume path)
                r.cell.save(r.frame, cs)

    # -- speculative draft waves --------------------------------------------

    def _speculate_idle_lanes(self) -> None:
        """One EXTRA packed wave that fills ONLY the lanes the tick's last
        run wave left idle (``ks[b] == 0``) with candidate-input draft
        branches, assigned by :class:`~.ops.batch.DraftWaveScheduler`.

        Each assigned lane loads its drafting lobby's pre-advance base state
        (a LazySlice gather into a functional COPY of the resident world —
        the live state is never touched), advances its candidate row
        ``depth`` frames, and the stacked outputs fill the lobby's branch
        cache for ``_do_loads``'s verified-hit servicing.  A tick with no
        idle lanes, or no predicted last advance, drafts nothing — drafts
        consume spare lanes, never widen the active bucket."""
        if self._last_wave is None:
            return
        prev_worlds, stacked, ks = self._last_wave
        adv = self._last_adv
        m = len(self.sessions)
        cfg = self.spec_config
        depth = max(cfg.depth, 1)
        idle = [b for b in range(m) if ks[b] == 0]
        if not idle:
            return
        wants = []
        cands_by_lobby: Dict[int, np.ndarray] = {}
        for b in range(m):
            a = adv[b]
            if not a or ks[b] == 0:
                continue
            last = a[-1]
            if not np.any(np.asarray(last.status) == InputStatus.PREDICTED):
                continue
            cands = np.asarray(
                cfg.candidates_fn(last.inputs), self.app.input_dtype
            )
            if cands.shape[0] == 0:
                continue
            cands_by_lobby[b] = cands
            wants.append((b, cands.shape[0]))
        if not wants:
            return
        plan = self._draft_sched.plan(idle, wants)
        if not plan:
            return
        rows = []
        for b, _ci, lane in plan:
            kb = ks[b]
            # the state feeding the lobby's LAST advance: the second-newest
            # stacked frame, or (single-advance waves) the pre-wave resident
            # row — same derivation as GgrsRunner's last_adv_src
            src = (
                LazySlice(stacked, (b, kb - 2)) if kb >= 2
                else LazySlice(prev_worlds, b)
            )
            rows.append((lane, src))
        with self._phases.phase("wave_dispatch"), span("DraftWaveBatched"):
            groups, fallback = plan_row_gather(rows)
            draft_worlds = self.worlds
            if groups:
                draft_worlds = fused_load_rows(draft_worlds, groups, None)
                self.device_dispatches += 1
                self._m_dispatches.inc()
            for lane, stored in fallback:
                draft_worlds = _set_row(
                    draft_worlds, lane, materialize(stored)
                )
                self.device_dispatches += 1
                self._m_dispatches.inc()
            from .ops.packing import pack_prefix, pack_row, repeat_last_row

            pspec = self.app.packed_spec
            packed = self._stage_packed_draft
            bucket = self._draft_bucket
            draft_ks = [0] * self._m_pad
            zero_status = np.zeros((self._np,), np.int8)
            for b, ci, lane in plan:
                lane_buf = packed[lane]
                pack_prefix(lane_buf, frame_add(self.frames[b], -1), depth)
                pack_row(
                    pspec, lane_buf, 0, cands_by_lobby[b][ci], zero_status
                )
                repeat_last_row(lane_buf, 1, bucket)
                draft_ks[lane] = depth
            for lane in range(self._m_pad):
                if draft_ks[lane] == 0:
                    # unassigned lanes must read n_real=0 even if a past
                    # draft wave left payload bytes behind
                    pack_prefix(packed[lane], 0, 0)
            _b, _finals, d_stacked, d_checks = self.exec.run_wave_packed(
                draft_worlds, packed, draft_ks
            )
            # finals are DISCARDED: drafts never touch the resident world
            self.device_dispatches += 1
            self._m_dispatches.inc()
            self.draft_waves += 1
            self._m_drafts.inc()
        import jax as _jax

        by_lobby: Dict[int, list] = {}
        for b, ci, lane in plan:
            by_lobby.setdefault(b, []).append((ci, lane))
        checks_m = d_checks.reshape(self._m_pad, bucket, 2)
        for b, pairs in by_lobby.items():
            lanes = np.array([lane for _ci, lane in pairs], np.int32)
            cands_b = np.stack(
                [cands_by_lobby[b][ci] for ci, _lane in pairs]
            )
            stacked_l = _jax.tree.map(lambda a: a[lanes, :depth], d_stacked)
            self.spec_caches[b].fill_from_branched(
                frame_add(self.frames[b], -1), cands_b, stacked_l,
                checks_m[lanes, :depth], offset=0, depth_eff=depth,
            )

    # -- observability ------------------------------------------------------

    def _report_mismatch(self, b: int, e: MismatchedChecksumError) -> None:
        """Lobby SyncTest mismatch: timeline event + forensics report."""
        telemetry.record(
            "checksum_mismatch", source="synctest", lobby=b,
            frames=list(e.mismatched_frames), current_frame=e.current_frame,
        )
        if telemetry.forensics_dir() is not None:
            # lobby_world is a device gather — only pay it when a report
            # will actually be written
            telemetry.write_desync_report(
                "synctest_mismatch", reg=self.app.reg,
                world=self.lobby_world(b), frames=e.mismatched_frames, lobby=b,
            )

    def arm_compile_guard(self) -> bool:
        """Declare warmup over: with ``BGT_COMPILE_GUARD=1`` (or
        :func:`~bevy_ggrs_tpu.utils.compile_guard.set_compile_guard`) any
        later wave-program compile raises
        :class:`~bevy_ggrs_tpu.utils.compile_guard.RecompileError` naming
        the owner/kind and bumps ``recompiles_steady_total{owner}``.
        Returns True when armed; no-op (False) when the guard is off."""
        return compile_guard.guard().arm()

    def stats(self) -> dict:
        """Driver + executor counters: ticks, rollbacks, device dispatches,
        fused/fallback load counts, per-lobby frame state, and the wave
        executor's compile/dispatch/bucket histogram stats."""
        out = {
            "lobbies": len(self.sessions),
            "packed": self.packed,
            "ticks": self.ticks,
            "rollbacks": self.rollbacks,
            "device_dispatches": self.device_dispatches,
            "fused_loads": self.fused_loads,
            "fallback_loads": self.fallback_loads,
            "stalled_frames": list(self.stalled),
            "frames": list(self.frames),
            "confirmed": list(self.confirmed),
            "phases": self._phases.totals(),
        }
        if self.spec_caches is not None:
            out["speculation"] = {
                "hits": sum(c.hits for c in self.spec_caches),
                "misses": sum(c.misses for c in self.spec_caches),
                "draft_waves": self.draft_waves,
                "draft_lanes_filled": self._draft_sched.lanes_filled,
                "dropped_candidates": self._draft_sched.dropped_candidates,
                "cache_served_frames": self.cache_served_frames,
                "cached_bytes": sum(
                    c.cached_bytes for c in self.spec_caches
                ),
            }
        if self.planner is not None:
            out["sharded"] = {
                "devices": self.planner.n_devices,
                "lanes_per_shard": self.planner.lanes_per_shard,
                "pad_lanes": self._m_pad - len(self.sessions),
                "imbalance_last": round(self.planner.last_imbalance, 4),
                "imbalance_max": round(self.planner.max_imbalance, 4),
                "waves_planned": self.planner.waves_planned,
            }
        out.update(self.exec.stats())
        return out

    def lobby_world(self, b: int):
        """Materialize lobby ``b``'s live world (one gather dispatch)."""
        return _row(self.worlds, b)

    def lobby_checksum(self, b: int) -> int:
        """Lobby ``b``'s live 64-bit world checksum (an allowlisted flush
        point: forces the fused batched pull — see snapshot/lazy.py —
        though a landed async copy makes it free)."""
        from .snapshot.checksum import checksum_to_int

        self._rbq.harvest()
        return checksum_to_int(self._world_checksum[b])

    def finish(self) -> None:
        """Flush deferred checksum comparisons on every lobby session."""
        self._rbq.harvest()
        for b, s in enumerate(self.sessions):
            if hasattr(s, "check_now"):
                try:
                    s.check_now()
                except MismatchedChecksumError as e:
                    self._report_mismatch(b, e)
                    if self.on_mismatch is not None:
                        self.on_mismatch(b, e)
                    else:
                        raise


# -- jitted row helpers (one dispatch each; compiled once) -------------------

_row_jit = None
_set_row_jit = None


def _row(tree, b: int):
    global _row_jit
    import jax

    if _row_jit is None:
        _row_jit = jax.jit(lambda t, i: jax.tree.map(lambda a: a[i], t))
    return _row_jit(tree, np.int32(b))


def _set_row(tree, b: int, row):
    global _set_row_jit
    import jax

    if _set_row_jit is None:
        _set_row_jit = jax.jit(
            lambda t, i, r: jax.tree.map(lambda a, x: a.at[i].set(x), t, r)
        )
    return _set_row_jit(tree, np.int32(b), row)
