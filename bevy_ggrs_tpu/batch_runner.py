"""BatchedRunner — the many-worlds game server driver.

The reference runs ONE session per process (`Session` is a singleton Bevy
resource, /root/reference/src/lib.rs:79-88); a server hosting M lobbies runs
M processes, each dispatching its own tiny sim.  A TPU inverts the economics:
one chip eats hundreds of small worlds per pass, and on remote-attached
devices the per-dispatch submission cost dominates small worlds — so M
serial dispatches are the one thing the server must not do.

This driver owns M sessions (any mix of SyncTest / P2P / in-process — they
only need the GgrsRequest protocol) over ONE resident ``[M, ...]`` stacked
world.  Each server tick it:

1. polls every session and collects its request list (host-side, cheap);
2. splits each lobby's list into an ordered sequence of ops —
   ``Load(frame)`` / ``Run([Save|Advance ...])`` — exactly the segments
   GgrsRunner fuses per lobby (runner.py _handle_requests);
3. executes ops positionally as WAVES across lobbies: wave w batches every
   lobby's w-th Run into ONE ``jit(vmap(resim_padded))`` dispatch
   (per-lobby ``n_real`` masks; idle lanes pass through), and serves Load
   ops host-side from per-lobby snapshot rings (with a fused gather path
   when every lobby loads out of the SAME past dispatch's stacked buffer —
   the lockstep-SyncTest shape).

Saves store ``LazySlice(stacked, (lobby, frame_idx))`` handles — one
``[M, K, ...]`` buffer per wave backs every lobby's ring rows, and checksum
pulls ride the process-wide BatchChecks fusion (snapshot/lazy.py).

Bit-equality caveat (same as ops/batch.py): the vmapped program is a
DIFFERENT XLA program than the single-lobby one, so for variant-unstable
float sims a batched lobby is not guaranteed bit-identical to a solo run of
the same inputs; integer/fixed-point sims and variant-stable steps (probe
with ops/variant_probe.py) batch exactly — proven by
tests/test_batched_runner.py against M independent GgrsRunners.  Canonical
modes are refused for the same reason (make_batched_resim_fn docstring).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from . import telemetry
from .app import App
from .ops.batch import make_batched_padded_fn, stack_worlds
from .ops.resim import pad_repeat_last
from .session.events import (
    DesyncDetected,
    MismatchedChecksumError,
    NotSynchronizedError,
    PredictionThresholdError,
    SessionState,
)
from .session.requests import AdvanceRequest, GgrsRequest, LoadRequest, SaveRequest
from .session.synctest import SyncTestSession
from .snapshot.lazy import BatchChecks, LazySlice, materialize
from .snapshot.ring import SnapshotRing
from .utils.frames import NULL_FRAME, frame_add
from .utils.tracing import span


class _Op:
    __slots__ = ("load_frame", "run")

    def __init__(self, load_frame=None, run=None):
        self.load_frame = load_frame  # int | None
        self.run = run  # List[GgrsRequest] | None


def _split_ops(requests: List[GgrsRequest]) -> List[_Op]:
    """[Load?](Advance|Save)* request list -> ordered Load/Run ops
    (the same maximal-run fusion as GgrsRunner._handle_requests)."""
    ops: List[_Op] = []
    i, n = 0, len(requests)
    while i < n:
        r = requests[i]
        if isinstance(r, LoadRequest):
            ops.append(_Op(load_frame=r.frame))
            i += 1
        else:
            j = i
            while j < n and isinstance(requests[j], (AdvanceRequest, SaveRequest)):
                j += 1
            ops.append(_Op(run=requests[i:j]))
            i = j
    return ops


class BatchedRunner:
    """M lobbies, one fused device dispatch per wave (module docstring)."""

    def __init__(
        self,
        app: App,
        sessions: Sequence,
        read_inputs: Optional[Callable[[int, List[int]], Dict[int, np.ndarray]]] = None,
        on_mismatch: Optional[Callable[[int, MismatchedChecksumError], None]] = None,
        on_event: Optional[Callable[[int, object], None]] = None,
        k_max: Optional[int] = None,
    ):
        if app.canonical_depth is not None or app.canonical_branches is not None:
            raise ValueError(
                "BatchedRunner is incompatible with canonical mode "
                "(see ops/batch.make_batched_resim_fn)"
            )
        self.app = app
        self.sessions = list(sessions)
        m = len(self.sessions)
        if m == 0:
            raise ValueError("BatchedRunner needs at least one session")
        # the deepest run any session can emit in one tick: a rollback spans
        # the full window plus the live advance
        windows = []
        for s in self.sessions:
            w = (
                s.rollback_window()
                if hasattr(s, "rollback_window")
                else s.max_prediction()
            )
            windows.append(max(w, s.max_prediction()))
            if self.app.retention < w:
                raise ValueError(
                    f"App(retention={self.app.retention}) < session rollback "
                    f"window ({w}) — see GgrsRunner.set_session"
                )
        self.k_max = k_max if k_max is not None else max(windows) + 1
        self.read_inputs = read_inputs or (
            lambda lobby, handles: {h: app.zero_inputs()[h] for h in handles}
        )
        self.on_mismatch = on_mismatch
        self.on_event = on_event
        self.worlds = stack_worlds([app.init_state() for _ in range(m)])
        self.fn = make_batched_padded_fn(app, self.k_max)
        # per-lobby live-world checksum handles (ONE vmapped dispatch for
        # all M rows; leading saves reuse these instead of dispatching)
        import jax as _jax

        from .snapshot.checksum import world_checksum as _wc

        self._batch_checksum_fn = _jax.jit(
            lambda ws: _jax.vmap(lambda w: _wc(app.reg, w))(ws)
        )
        init_batch = BatchChecks(self._batch_checksum_fn(self.worlds))
        self._world_checksum = [init_batch.ref(b) for b in range(m)]
        self.rings = [SnapshotRing(depth=max(windows) + 2) for _ in range(m)]
        self.frames = [0] * m  # per-lobby RollbackFrameCount
        self.confirmed = [NULL_FRAME] * m
        self.ticks = 0
        self.rollbacks = 0
        self.device_dispatches = 0
        self.stalled = [0] * m
        self._np = self.sessions[0].num_players()
        for s in self.sessions:
            if s.num_players() != self._np:
                raise ValueError("all lobbies must share num_players "
                                 "(one batched input tensor)")

    # -- per-tick driver ----------------------------------------------------

    def tick(self) -> None:
        """One server tick: poll + step every lobby, flush as waves."""
        self.ticks += 1
        telemetry.count(
            "server_ticks_total", help="batched-server ticks (all lobbies)"
        )
        per_lobby_ops: List[List[_Op]] = []
        for b, s in enumerate(self.sessions):
            per_lobby_ops.append(self._collect_ops(b, s))
        n_waves = max((len(ops) for ops in per_lobby_ops), default=0)
        for w in range(n_waves):
            wave_ops = [
                ops[w] if w < len(ops) else None for ops in per_lobby_ops
            ]
            self._do_loads(wave_ops)
            self._do_runs(wave_ops)
        for b, s in enumerate(self.sessions):
            cf = s.confirmed_frame()
            self.confirmed[b] = cf
            self.rings[b].confirm(cf)

    def _collect_ops(self, b: int, s) -> List[_Op]:
        if hasattr(s, "poll_remote_clients"):
            s.poll_remote_clients()
        if hasattr(s, "events") and (
            self.on_event is not None or telemetry.enabled()
        ):
            for ev in s.events():
                if isinstance(ev, DesyncDetected):
                    telemetry.record(
                        "checksum_mismatch", source="p2p", lobby=b,
                        frames=[ev.frame], local_checksum=ev.local_checksum,
                        remote_checksum=ev.remote_checksum, addr=repr(ev.addr),
                    )
                    if telemetry.forensics_dir() is not None:
                        # lobby_world is a device gather — only pay it when
                        # a report will actually be written
                        telemetry.write_desync_report(
                            "p2p_desync", reg=self.app.reg,
                            world=self.lobby_world(b), frames=[ev.frame],
                            local_checksum=ev.local_checksum,
                            remote_checksum=ev.remote_checksum, addr=ev.addr,
                            lobby=b,
                        )
                if self.on_event is not None:
                    self.on_event(b, ev)
        if isinstance(s, SyncTestSession):
            handles = list(range(s.num_players()))
        else:
            if s.current_state() != SessionState.RUNNING:
                return []  # still handshaking: poll only
            handles = list(s.local_player_handles())
        for h, v in self.read_inputs(b, handles).items():
            s.add_local_input(h, v)
        try:
            with span("SessionAdvanceFrame"):
                requests = s.advance_frame()
        except MismatchedChecksumError as e:
            self._report_mismatch(b, e)
            if self.on_mismatch is not None:
                self.on_mismatch(b, e)
                return []
            raise
        except PredictionThresholdError:
            self.stalled[b] += 1
            telemetry.count(
                "stalled_frames_total", help="ticks skipped on stall",
                kind="p2p", lobby=b,
            )
            telemetry.record("stall", lobby=b, frame=self.frames[b],
                             reason="prediction_threshold")
            return []
        except NotSynchronizedError:
            return []
        return _split_ops(requests)

    # -- loads --------------------------------------------------------------

    def _do_loads(self, wave_ops: List[Optional[_Op]]) -> None:
        loads = [
            (b, op.load_frame)
            for b, op in enumerate(wave_ops)
            if op is not None and op.load_frame is not None
        ]
        if not loads:
            return
        self.rollbacks += len(loads)
        if telemetry.enabled():
            for b, f in loads:
                telemetry.count("rollbacks_total", help="LoadRequests executed",
                                lobby=b)
                telemetry.observe(
                    "rollback_depth", self.frames[b] - f,
                    "frames rolled back per LoadRequest", lobby=b,
                )
                telemetry.record("rollback", lobby=b, to_frame=f,
                                 from_frame=self.frames[b],
                                 depth=self.frames[b] - f)
        with span("LoadWorldBatched"):
            fused = self._try_fused_load(loads)
            if fused is not None:
                self.worlds = fused
                for b, f in loads:
                    _, cs = self.rings[b].rollback(f)
                    self._world_checksum[b] = cs
            else:
                for b, f in loads:
                    stored, cs = self.rings[b].rollback(f)
                    state = self.app.reg.load_state(materialize(stored))
                    self.worlds = _set_row(self.worlds, b, state)
                    self._world_checksum[b] = cs
            for b, f in loads:
                self.frames[b] = f

    def _try_fused_load(self, loads):
        """Lockstep fast path: every lobby rolls back to a row of the SAME
        past dispatch's ``[M, K, ...]`` stacked buffer at the same frame
        index, with lane == lobby (the M-identical-SyncTest shape) — one
        gather replaces M scatters."""
        if len(loads) != len(self.sessions):
            return None
        if not self.app.reg.is_identity_strategy():
            return None
        src = None
        idx = None
        for b, f in loads:
            stored, _ = self.rings[b].rollback(f)
            if not (isinstance(stored, LazySlice)
                    and isinstance(stored._i, tuple)):
                return None
            bb, ii = stored._i
            if bb != b:
                return None
            if src is None:
                src, idx = stored._stacked, ii
            elif stored._stacked is not src or ii != idx:
                return None
        return _gather_frame(src, idx)

    # -- runs ---------------------------------------------------------------

    def _do_runs(self, wave_ops: List[Optional[_Op]]) -> None:
        m = len(self.sessions)
        runs = [op.run if op is not None else None for op in wave_ops]
        adv = [
            [r for r in (run or []) if isinstance(r, AdvanceRequest)]
            for run in runs
        ]
        ks = [len(a) for a in adv]
        if not any(run for run in runs):
            return
        k_hot = max(ks)
        if k_hot > self.k_max:
            raise ValueError(
                f"lobby requested a {k_hot}-frame run > k_max={self.k_max}; "
                "raise BatchedRunner(k_max=...)"
            )
        identity = self.app.reg.is_identity_strategy()
        stacked = batch = None
        pre_checksum = list(self._world_checksum)
        prev_worlds = self.worlds
        if k_hot > 0:
            inputs = np.zeros(
                (m, self.k_max, self._np, *self.app.input_shape),
                self.app.input_dtype,
            )
            status = np.zeros((m, self.k_max, self._np), np.int8)
            n_real = np.zeros((m,), np.int32)
            starts = np.asarray(self.frames, np.int32)
            for b, a in enumerate(adv):
                if not a:
                    continue
                seq = np.stack([x.inputs for x in a])
                st = np.stack([x.status for x in a])
                inputs[b] = pad_repeat_last(seq, self.k_max - len(a))
                status[b] = pad_repeat_last(st, self.k_max - len(a))
                n_real[b] = len(a)
            self.device_dispatches += 1
            telemetry.count("device_dispatches_total",
                            help="fused resim dispatches")
            telemetry.count(
                "resim_frames_total", sum(max(k - 1, 0) for k in ks),
                help="frames resimulated beyond the first of each dispatch",
            )
            telemetry.record(
                "dispatch", batched=True, k_hot=k_hot,
                active_lobbies=sum(1 for k in ks if k > 0),
            )
            with span("AdvanceWorldBatched"):
                finals, stacked, checks_flat = self.fn(
                    self.worlds, inputs, status, starts, n_real
                )
                batch = BatchChecks(checks_flat)
                self.worlds = finals
                for b in range(m):
                    if ks[b] > 0:
                        self.frames[b] = frame_add(self.frames[b], ks[b])
                        self._world_checksum[b] = batch.ref(
                            b * self.k_max + ks[b] - 1
                        )
        with span("SaveWorldBatched"):
            for b, run in enumerate(runs):
                if not run:
                    continue
                c = 0
                for r in run:
                    if isinstance(r, AdvanceRequest):
                        c += 1
                        continue
                    if c == 0:
                        # pre-dispatch save: slice the PREVIOUS resident
                        # world's row (still alive in prev_worlds); its
                        # checksum handle was tracked, not recomputed
                        state_s = LazySlice(prev_worlds, b)
                        cs = pre_checksum[b]
                    else:
                        cs = batch.ref(b * self.k_max + (c - 1))
                        state_s = LazySlice(stacked, (b, c - 1))
                    stored = (
                        state_s
                        if identity
                        else self.app.reg.store_state(state_s.materialize())
                    )
                    self.rings[b].push(r.frame, (stored, cs))
                    r.cell.save(r.frame, cs.to_int)

    # -- observability ------------------------------------------------------

    def _report_mismatch(self, b: int, e: MismatchedChecksumError) -> None:
        """Lobby SyncTest mismatch: timeline event + forensics report."""
        telemetry.record(
            "checksum_mismatch", source="synctest", lobby=b,
            frames=list(e.mismatched_frames), current_frame=e.current_frame,
        )
        if telemetry.forensics_dir() is not None:
            # lobby_world is a device gather — only pay it when a report
            # will actually be written
            telemetry.write_desync_report(
                "synctest_mismatch", reg=self.app.reg,
                world=self.lobby_world(b), frames=e.mismatched_frames, lobby=b,
            )

    def stats(self) -> dict:
        return {
            "lobbies": len(self.sessions),
            "ticks": self.ticks,
            "rollbacks": self.rollbacks,
            "device_dispatches": self.device_dispatches,
            "stalled_frames": list(self.stalled),
            "frames": list(self.frames),
            "confirmed": list(self.confirmed),
        }

    def lobby_world(self, b: int):
        """Materialize lobby ``b``'s live world (one gather dispatch)."""
        return _row(self.worlds, b)

    def lobby_checksum(self, b: int) -> int:
        """Lobby ``b``'s live 64-bit world checksum (forces the fused
        batched pull — see snapshot/lazy.py)."""
        from .snapshot.checksum import checksum_to_int

        return checksum_to_int(self._world_checksum[b])

    def finish(self) -> None:
        """Flush deferred checksum comparisons on every lobby session."""
        for b, s in enumerate(self.sessions):
            if hasattr(s, "check_now"):
                try:
                    s.check_now()
                except MismatchedChecksumError as e:
                    self._report_mismatch(b, e)
                    if self.on_mismatch is not None:
                        self.on_mismatch(b, e)
                    else:
                        raise


# -- jitted row helpers (one dispatch each; compiled once) -------------------

_row_jit = None
_set_row_jit = None
_gather_frame_jit = None


def _row(tree, b: int):
    global _row_jit
    import jax

    if _row_jit is None:
        _row_jit = jax.jit(lambda t, i: jax.tree.map(lambda a: a[i], t))
    return _row_jit(tree, np.int32(b))


def _set_row(tree, b: int, row):
    global _set_row_jit
    import jax

    if _set_row_jit is None:
        _set_row_jit = jax.jit(
            lambda t, i, r: jax.tree.map(lambda a, x: a.at[i].set(x), t, r)
        )
    return _set_row_jit(tree, np.int32(b), row)


def _gather_frame(stacked, i: int):
    """[M, K, ...] stacked -> [M, ...] at frame index i (lockstep load)."""
    global _gather_frame_jit
    import jax

    if _gather_frame_jit is None:
        _gather_frame_jit = jax.jit(
            lambda t, ii: jax.tree.map(lambda a: a[:, ii], t)
        )
    return _gather_frame_jit(stacked, np.int32(i))
