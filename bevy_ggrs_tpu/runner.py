"""GgrsRunner — the schedule driver (``run_ggrs_schedules`` analog,
/root/reference/src/schedule_systems.rs:19-289).

Owns the fixed-timestep accumulator (ns-precision period, run-slow x11/10 —
schedule_systems.rs:31-38), polls remote clients every host tick, steps the
session, and dispatches its request stream to the device.

The key TPU-first move is in :meth:`_handle_requests`: the reference executes
every request as a separate host-ECS schedule run (:189-270); here a maximal
``[Load?] (Advance|Save)*`` run is fused into ONE compiled ``lax.scan`` call
that returns all intermediate states and checksums — a rollback of depth N is
one device dispatch.  Checksums are handed to the session as lazy providers so
device->host syncs only happen when the protocol needs the value."""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import numpy as np

from . import telemetry
from .app import App
from .session.events import (
    DesyncDetected,
    InputStatus,
    InvalidRequestError,
    MismatchedChecksumError,
    NotSynchronizedError,
    PredictionThresholdError,
    SessionState,
)
from .session.requests import AdvanceRequest, GgrsRequest, LoadRequest, SaveRequest
from .session.synctest import SyncTestSession
from .snapshot.checksum import checksum_to_int
from .snapshot.lazy import (
    BatchChecks,
    LazySlice,
    materialize,
    readback_queue,
    wrap_single_checksum,
)
from .snapshot.ring import SnapshotRing
from .ops.resim import slice_frame
from .ops.speculation import SpeculationCache, SpeculationConfig
from .utils import compile_guard
from .utils.frames import NULL_FRAME, frame_add
from .utils.tracing import span, trace_log


class GgrsRunner:
    """The schedule driver: fixed-timestep loop, session stepping, fused request dispatch (see module docstring)."""
    def __init__(
        self,
        app: App,
        session=None,
        read_inputs: Optional[Callable[[List[int]], Dict[int, np.ndarray]]] = None,
        on_event: Optional[Callable] = None,
        on_mismatch: Optional[Callable[[MismatchedChecksumError], None]] = None,
        initial_state=None,
        speculation: Optional[SpeculationConfig] = None,
        on_advance: Optional[Callable] = None,
        on_confirmed: Optional[Callable[[int], None]] = None,
        coalesce_frames: int = 1,
        pipeline: bool = True,
        packed: Optional[bool] = None,
        megastep: bool = False,
        input_queue: bool = False,
        measure_rollback_service: bool = False,
    ):
        self.app = app
        self.read_inputs = read_inputs or (lambda handles: {h: app.zero_inputs()[h] for h in handles})
        self.on_event = on_event
        self.on_mismatch = on_mismatch
        self.on_advance = on_advance  # (frame, inputs, status) per AdvanceFrame
        self.on_confirmed = on_confirmed  # (frame) when confirmed advances
        self.world = initial_state if initial_state is not None else app.init_state()
        if initial_state is not None and not app.reg.is_identity_strategy():
            # same canonicalization App.init_state applies: the frame-0
            # snapshot must restore exactly the live state (lossy strategies)
            self.world = app.reg.load_state(app.reg.store_state(self.world))
        self._world_checksum = wrap_single_checksum(app.checksum_fn(self.world))
        self.ring: SnapshotRing = SnapshotRing(depth=8)
        self.frame = 0  # RollbackFrameCount
        self.confirmed = NULL_FRAME  # ConfirmedFrameCount
        self.accumulator = 0.0
        self.run_slow = False
        self.local_players: List[int] = []
        self.events: List = []
        self.session = None
        self.stalled_frames = 0  # PredictionThreshold skips (observability)
        # Tick coalescing: when one host update owes N > 1 sim frames (the
        # run-behind / fast-forward / catch-up shapes), collect all N ticks'
        # session requests and flush them through ONE _handle_requests call
        # — consecutive advances fuse into a single k=N dispatch instead of
        # N submissions (on remote-attached devices each submission costs
        # flat link latency; the spectator's catchup path already emits
        # multi-advance lists and proves the fused shape).  1 = flush every
        # tick (the reference cadence).  Variant note: k already varies with
        # rollback depth, so coalescing adds no NEW program-variant risk
        # beyond what rollbacks pose (canonical mode pads either way), but
        # canonical apps must keep coalesce_frames + window <= depth.
        if coalesce_frames < 1:
            raise ValueError("coalesce_frames must be >= 1")
        self.coalesce_frames = coalesce_frames
        if (
            speculation is not None
            and app.canonical_depth is not None
            and app.canonical_branches is None
        ):
            raise ValueError(
                "speculation under bit-determinism requires the canonical-"
                "branched program: set App(canonical_branches=M+1) so hedges "
                "run inside the SAME fixed [branches, depth] dispatch every "
                "peer uses (docs/determinism.md)"
            )
        self.spec_cache = (
            SpeculationCache(app, speculation) if speculation is not None else None
        )
        # ordered cache-maintenance ops — ("inv", frame) invalidations and
        # ("spec", src_fn, ring_handle, start_frame, inputs) hedges —
        # recorded during request handling, applied in recorded order by
        # _flush_speculation at the next seam
        self._pending_speculate = []
        # observability counters (network_stats covers the wire; these cover
        # the sim driver — rollback frequency/depth is THE rollback-netcode
        # health metric)
        self.ticks = 0
        self.rollbacks = 0
        self.rollback_frames = 0  # total resimulated frames
        self.device_dispatches = 0
        self.donated_dispatches = 0  # dispatches that donated the input world
        # HBM guard for lazy ring saves: storing LazySlice handles keeps the
        # whole [k, ...] stacked resim buffer alive while ANY of its frames
        # is ringed — O(ring_depth x k) world copies worst case.  Above this
        # per-dispatch stacked-buffer size the driver materializes each save
        # (one extra device-side copy per frame, no host transfer), bounding
        # ring memory to O(ring_depth) worlds.  Small worlds keep the lazy
        # handles (the per-slice dispatch is the cost that matters there).
        self.ring_materialize_bytes = 64 * 2**20
        # Buffer donation: when provably safe, the dispatch donates the input
        # state's buffers so XLA reuses them in place instead of allocating a
        # fresh world per dispatch (round-1 NOTES gap #4).  Safe = the state
        # object is referenced ONLY by self.world: False at init (the caller
        # may hold the initial state) and after any event that aliases the
        # world with the ring; True after every dispatch/rollback that leaves
        # self.world holding a freshly materialized buffer.  Speculation
        # paths retain the pre-state after dispatch, so donation stays off
        # whenever a SpeculationCache is attached.
        self.enable_donation = True
        self._world_donatable = False
        self._last_stacked = None  # previous dispatch's stacked saves
        self._last_k = 0
        self._last_stacked_frame: Optional[int] = None
        # Tick pipelining (docs/architecture.md "Tick pipeline"): dispatch
        # frame N's fused program, start its checksum readback as a
        # NON-blocking async copy, and do tick N+1's host work (network
        # poll, input collection, ring bookkeeping on lazy refs) before
        # anything touches N's outputs.  Landed copies are harvested at the
        # top of each update(); the in-flight window is one dispatch deep
        # (the next dispatch's XLA data dependency on `final` serializes
        # naturally).  pipeline=False restores the pre-pipeline synchronous
        # shape (no async starts, no harvest) — the bench's sync baseline.
        self.pipeline = bool(pipeline)
        self._rbq = readback_queue()
        self.pipeline_degrades = 0  # loads that targeted in-flight output
        # Persistent solo-runner staging (the BatchedRunner's pinned-buffer
        # pattern): steady-state ticks fill these in place instead of
        # allocating a fresh np.stack per dispatch.  Sized lazily from the
        # first dispatch, grown geometrically when a deeper run appears;
        # jit sees the same [k, ...] shapes np.stack produced (views of the
        # capacity buffer), so no new trace variants.  Safe to reuse across
        # dispatches: jax copies numpy arguments to device buffers at call
        # time (the BatchedRunner has shipped this shape since PR 2).
        self._stage_inputs: Optional[np.ndarray] = None
        self._stage_status: Optional[np.ndarray] = None
        self._stage_cap = 0
        # Packed single-upload staging (ops/packing.py): the three per-
        # dispatch uploads (inputs, status, frame scalar) fuse into ONE
        # persistent int8 buffer split in-program by a pure bitcast —
        # killing 2/3 of the per-tick link-latency share the dispatch-floor
        # census attributed to uploads (docs/dispatch_floor.md).  Tri-state:
        # None (the default) auto-falls-back to the unpacked path when the
        # app has no packed program (canonical_branches mode); an EXPLICIT
        # packed=True raises instead of silently degrading — the mode
        # matrix in docs/architecture.md "Speculative rollback servicing".
        if packed is None:
            self.packed = app.packed_resim_fn is not None
        else:
            self.packed = bool(packed)
            if self.packed and app.packed_resim_fn is None:
                raise ValueError(
                    "packed=True but the app ships no packed program "
                    "(canonical_branches keeps its own [B, K] dispatch "
                    "shape); pass packed=None to allow the automatic "
                    "three-upload fallback — see the mode matrix in "
                    "docs/architecture.md"
                )
        self._stage_packed: Optional[np.ndarray] = None
        self._packed_cap = 0
        # Device-resident input queue (utils/staging.StagingQueue): rotate
        # the packed staging buffers so the per-upload transfer block
        # overlaps the NEXT tick's host work instead of stalling this one
        self.input_queue = bool(input_queue)
        if self.input_queue and not self.packed:
            raise ValueError(
                "input_queue rotates the packed staging buffer and so "
                "requires the packed upload path; enable packed (or drop "
                "input_queue) — see the mode matrix in docs/architecture.md"
            )
        self._packed_queue = None  # StagingQueue, sized lazily
        # Honest rollback-servicing latency (bench.py stage_speculation):
        # close the async-dispatch window inside the measured span so the
        # hit/miss rollback_service_ms histograms compare retired work
        self.measure_rollback_service = bool(measure_rollback_service)
        self.cache_served_frames = 0  # rollback frames served from cache
        # Upload census (always-on plain ints, like device_dispatches):
        # host->device array uploads issued by fused dispatches, and total
        # bytes staged through packed buffers — the numbers the bench.py
        # "uploads" stage gates on
        self.host_uploads = 0
        self.packed_upload_bytes = 0
        _treg = telemetry.registry()
        self._m_uploads = _treg.bind_histogram(
            "uploads_per_dispatch",
            "host->device uploads issued per fused dispatch (1 on the "
            "packed path)",
            buckets=(1, 2, 3, 4, 8),
        )
        self._m_packed_bytes = _treg.bind_counter(
            "packed_upload_bytes",
            "bytes staged through packed single-upload buffers",
        )
        # Device-resident megastep (ops/megastep.py): opt-in mode where a
        # whole coalesced flush — including the rollback load, when its
        # target is still resident in the on-device snapshot ring — runs as
        # ONE dispatch fed by ONE packed upload.  The host keeps a
        # slot->frame mirror of the device ring; misses fall back to the
        # host ring's materialize path (bit-identical by construction).
        self.megastep = bool(megastep)
        if self.megastep:
            if not app.reg.is_identity_strategy():
                raise ValueError(
                    "megastep requires an identity snapshot strategy: the "
                    "device ring stores live stacked states, and a lossy "
                    "strategy's store/load round-trip would need to run "
                    "inside the ring select"
                )
            if speculation is not None:
                raise ValueError(
                    "megastep and speculation are mutually exclusive (the "
                    "megastep flush has no per-frame lookup seam)"
                )
            if app.canonical_branches is not None:
                raise ValueError(
                    "megastep is incompatible with canonical_branches "
                    "(the branched program owns its own dispatch shape)"
                )
            # the ring aliases every recent state, so donation is never safe
            self.enable_donation = False
        self.megastep_dispatches = 0
        self.fused_ring_loads = 0  # rollbacks served from the device ring
        self._ms_fn = None
        self._ms_ring = None
        self._ms_ring_frames = None
        self._ms_k = 0  # megastep program depth (k_max)
        self._ms_slots = 0  # device ring depth R
        self._dev_frames: Dict[int, int] = {}  # slot -> resident frame
        # stacked-save device bytes depend only on the dispatch depth k
        # (shapes are static per app), so compute once per depth instead of
        # walking the pytree every tick
        self._stacked_bytes_by_k: dict = {}
        # Tick-phase attribution (telemetry/phases.py): guarded timers per
        # hot-loop phase feeding the always-on flight recorder and — while
        # telemetry is enabled — the tick_phase_ms histograms.  compile_ms
        # keeps first-dispatch wall time per program variant (the trace+
        # compile cost of the make_*_fn-built programs, paid at first call).
        self._phases = telemetry.PhaseSet(owner="solo")
        self.compile_ms: Dict[str, float] = {}
        self._seen_variants: set = set()
        # Periodic per-peer NetworkStats/TimeSync sampler (telemetry/
        # netstats.py); attached by set_session for sessions that expose
        # network_stats, polled inside the net_poll phase
        self._netstats = None
        # device-memory accounting namespace (telemetry/devmem.py): the
        # ring / megastep-ring / staging owners live under this tag and die
        # with the runner, so long processes never accumulate stale rows
        import weakref

        self._devmem_tag = telemetry.devmem.scope("solo")
        weakref.finalize(self, telemetry.devmem.forget_scope, self._devmem_tag)
        self._world_nbytes = 0  # one world's device footprint (set_session)
        if session is not None:
            self.set_session(session)

    # -- live world access ---------------------------------------------------

    @property
    def world(self):
        """The live WorldState.  Assigning to it (the supported
        external-write pattern, e.g. desync-injection tests) marks the
        state non-donatable: the caller may still hold references to the
        buffers, so the next dispatch must not hand them to XLA."""
        return self._world

    @world.setter
    def world(self, value) -> None:
        """Replace the live world; externally-set states are never donated
        (the caller may hold references to their buffers)."""
        self._world = value
        self._world_donatable = False

    # -- session lifecycle (restart semantics, schedule_systems.rs:70-79) ---

    def set_session(self, session) -> None:
        """Insert (or replace) the session; None resets driver state the way
        removing the ``Session`` resource does in the reference.

        An outgoing session with deferred checksum comparison is flushed
        first so no frame leaves the driver uncompared."""
        if self.session is not None and self.session is not session:
            self._flush_session_checks()
        self.session = session
        self.accumulator = 0.0
        self.run_slow = False
        self.local_players = []
        self.frame = 0
        self.confirmed = NULL_FRAME
        self.ring.clear()
        self._last_stacked = None
        self._last_stacked_frame = None
        # megastep device-ring state is sized from the session's windows;
        # a new session rebuilds it lazily at the first flush
        self._ms_fn = None
        self._ms_ring = None
        self._ms_ring_frames = None
        self._dev_frames = {}
        if session is not None:
            # despawn-retirement safety invariant (ops/resim.py docstring):
            # slots hard-freed at frame-retention must never sit inside the
            # rollback window, or a rollback could restore a snapshot whose
            # despawn the corrected inputs would have cancelled
            mp = session.max_prediction()
            window = (
                session.rollback_window()
                if hasattr(session, "rollback_window")
                else mp
            )
            if self.app.retention < window:
                raise ValueError(
                    f"App(retention={self.app.retention}) < session rollback "
                    f"window ({window}): raise retention to at least the "
                    "deepest rollback the session can request (see "
                    "ops/resim.py despawn-retirement invariant)"
                )
            if (
                self.app.canonical_depth is not None
                and self.coalesce_frames + window > self.app.canonical_depth
            ):
                # a rollback landing in the same coalesced flush as catch-up
                # ticks fuses a (window + coalesce)-long run; the canonical
                # program cannot pad past its fixed depth, so failing here
                # beats a timing-dependent crash minutes into a session
                raise ValueError(
                    f"coalesce_frames ({self.coalesce_frames}) + rollback "
                    f"window ({window}) exceeds canonical_depth "
                    f"({self.app.canonical_depth}); lower coalesce_frames or "
                    "raise App(canonical_depth=...)"
                )
            if (
                isinstance(session, SyncTestSession)
                and self.coalesce_frames
                > session.check_distance + session.compare_interval() + 2
            ):
                # the session GCs comparison cells check_distance +
                # compare_interval + 2 frames back every advance; a deeper
                # flush cadence would land resim checksums AFTER the cell
                # was collected, silently skipping those comparisons — the
                # determinism oracle must fail loudly instead of thinning
                raise ValueError(
                    f"coalesce_frames ({self.coalesce_frames}) exceeds the "
                    "SyncTest comparison-cell horizon (check_distance + "
                    "compare_interval + 2 = "
                    f"{session.check_distance + session.compare_interval() + 2}"
                    "); lower coalesce_frames or raise check_distance/"
                    "compare_interval"
                )
            # ring must hold a snapshot window frames back even if a session
            # reports rollback_window > max_prediction
            self.ring.set_depth(self._ring_depth(session))
            # device-memory accounting: ring residency = stored snapshots x
            # one world's footprint (docs/observability.md "Tracing &
            # device memory"); shapes are static so compute the unit once
            from .utils.mem import tree_device_bytes

            self._world_nbytes = tree_device_bytes(self._world)
            self.ring.set_accounting(
                self._devmem_tag + "/snapshot_ring", self._world_nbytes
            )
            # sessions may start at a nonzero frame (wraparound tests, resumed
            # sessions); mirror it so ctx.frame/time agree from tick one
            cur = getattr(session, "current_frame", 0)
            self.frame = cur() if callable(cur) else cur
        if session is not None and hasattr(session, "network_stats"):
            from .telemetry.netstats import NetStatsSampler

            self._netstats = NetStatsSampler(session)
        else:
            self._netstats = None

    def _ring_depth(self, session) -> int:
        """Snapshot-ring capacity: the deepest rollback window the session
        can request, plus every save a maximally coalesced flush can push
        before the end-of-flush confirm prunes (one formula — a second copy
        drifting from this one is how rings get undersized)."""
        mp = session.max_prediction()
        window = (
            session.rollback_window()
            if hasattr(session, "rollback_window")
            else mp
        )
        return max(mp, window) + 1 + self.coalesce_frames

    def _flush_session_checks(self) -> None:
        """Force any deferred checksum comparisons on the current session,
        routing a mismatch to ``on_mismatch`` like a ticking one would."""
        s = self.session
        if s is None or not hasattr(s, "check_now"):
            return
        # free harvest first: copies that already landed won't count as
        # forced readbacks in the flush below
        self._rbq.harvest()
        try:
            s.check_now()
        except MismatchedChecksumError as e:
            trace_log("SyncTest mismatch (flush): %s", e)
            self._report_mismatch(e)
            if self.on_mismatch is not None:
                self.on_mismatch(e)
            else:
                raise

    def finish(self) -> None:
        """End-of-run hook: flush deferred checksum comparisons (SyncTest
        with ``compare_interval`` > 1 would otherwise leave the final window
        of frames uncompared — see docs/debugging-desyncs.md §1)."""
        self._flush_session_checks()

    # -- fixed-timestep driver (schedule_systems.rs:19-83) ------------------

    def update(self, delta_seconds: float) -> None:
        """One host tick: accumulate time, poll the network, run 0+ GGRS frames."""
        fps_delta = (1.0 / self.app.fps) * (1.1 if self.run_slow else 1.0)
        self.accumulator += delta_seconds
        if self.session is None:
            self.accumulator = 0.0
            return
        ph = self._phases
        ph.begin_tick()
        if self.pipeline:
            # collect last tick's landed checksum copies BEFORE the network
            # poll, so the session's desync driver publishes them this tick
            # without ever blocking on the device
            with ph.phase("readback_harvest"):
                self._rbq.harvest()
        if hasattr(self.session, "poll_remote_clients"):
            with ph.phase("net_poll"):
                with span("PollRemoteClients"):
                    self.session.poll_remote_clients()
                self._drain_events()
                if self._netstats is not None:
                    self._netstats.poll()
                if telemetry.enabled():
                    self._record_network_stats()
        pending: List[GgrsRequest] = []
        pending_ticks = 0
        ran_requests = False
        stepped = 0
        while self.accumulator >= fps_delta:
            self.accumulator -= fps_delta
            stepped += 1
            if hasattr(self.session, "frames_ahead"):
                self.run_slow = self.session.frames_ahead() > 0
            with ph.phase("session_step"):
                reqs = self._step_session()
            if reqs:
                pending.extend(reqs)
                pending_ticks += 1
                if pending_ticks >= self.coalesce_frames:
                    self._handle_requests(pending)
                    pending = []
                    pending_ticks = 0
                    ran_requests = True
            fps_delta = (1.0 / self.app.fps) * (1.1 if self.run_slow else 1.0)
        if pending:
            self._handle_requests(pending)
            ran_requests = True
        if ran_requests and not self.pipeline:
            # synchronous mode: zero-deep in-flight window — retire this
            # tick's device work (world + checksum readback) before the
            # driver returns, exactly the behavior pipelining replaces
            with ph.phase("readback_harvest"):
                self._drain_inflight()
        if stepped:
            # idle accumulator polls (sub-frame deltas, handshake spins)
            # don't flood the flight ring with empty entries.  The counter
            # stamps (device residency, in-flight readbacks) feed the
            # Chrome-trace counter tracks (telemetry/trace.py); guarded on
            # the recording gate so the fully-disabled path computes nothing
            if ph.on:
                ph.end_tick(
                    frame=self.frame,
                    device_bytes=telemetry.devmem.total(),
                    pipeline_depth=self._rbq.depth() if self.pipeline else 0,
                )
            else:
                ph.end_tick(frame=self.frame)

    @property
    def checksum(self) -> int:
        """Current world checksum as the 64-bit cross-peer value (the
        user-readable ``Checksum`` resource analog, checksum.rs:48-56).
        Forces a device sync (an allowlisted flush point — free when the
        async copy already landed)."""
        if self.pipeline:
            self._rbq.harvest()
        return checksum_to_int(self._world_checksum)

    def _drain_inflight(self) -> None:
        """Flush the in-flight window: collect landed async readbacks and
        block until the live world's dispatch completes, so external reads
        observe the post-dispatch state (allowlisted in the hot-loop purity
        lint — this IS the blocking point)."""
        import jax

        if self.pipeline:
            self._rbq.harvest()
        else:
            # synchronous mode: retire checksum readbacks with the tick —
            # these count as forced (blocking) pulls in the readback stats
            BatchChecks.pull_pending()
        jax.block_until_ready(self._world.comps)

    def read_components(self, names=None) -> dict:
        """Fetch component columns (and the active mask) to host numpy in one
        transfer — the render-readback path.  ``names=None`` fetches all.
        Drains the in-flight dispatch window first so a mid-pipeline read
        can't observe a stale world."""
        import jax

        from .snapshot.world import active_mask

        self._drain_inflight()
        names = list(names) if names is not None else list(self.app.reg.components)
        arrays = {n: self.world.comps[n] for n in names}
        for n in names:
            arrays[f"__has_{n}__"] = self.world.has[n]
        arrays["__active__"] = active_mask(self.world)
        out = jax.device_get(arrays)
        return {k: np.asarray(v) for k, v in out.items()}

    def profile(self, logdir: str):
        """Context manager: capture a jax profiler trace of driver activity
        (device side of the span log — view with TensorBoard/XProf)."""
        import contextlib

        import jax

        @contextlib.contextmanager
        def cm():
            with jax.profiler.trace(logdir):
                yield self

        return cm()

    def stats(self) -> dict:
        """Driver health counters (rollback frequency/depth, dispatches,
        stalls, speculation hit rate)."""
        return {
            "overflow": bool(np.asarray(self.world.overflow)),
            "ticks": self.ticks,
            "rollbacks": self.rollbacks,
            "resimulated_frames": self.rollback_frames,
            "device_dispatches": self.device_dispatches,
            "donated_dispatches": self.donated_dispatches,
            "host_uploads": self.host_uploads,
            "packed": self.packed,
            "packed_upload_bytes": self.packed_upload_bytes,
            "megastep": self.megastep,
            "megastep_dispatches": self.megastep_dispatches,
            "fused_ring_loads": self.fused_ring_loads,
            "stalled_frames": self.stalled_frames,
            "speculation_hits": getattr(self.spec_cache, "hits", 0),
            "speculation_misses": getattr(self.spec_cache, "misses", 0),
            "speculation_cached_bytes": getattr(self.spec_cache, "cached_bytes", 0),
            "speculation_draft_dispatches": getattr(
                self.spec_cache, "draft_dispatches", 0
            ),
            "cache_served_frames": self.cache_served_frames,
            "input_queue": self.input_queue,
            "staging_deferred_blocks": getattr(
                self._packed_queue, "deferred_blocks", 0
            ),
            "staging_landed_free": getattr(
                self._packed_queue, "landed_free", 0
            ),
            "frame": self.frame,
            "confirmed": self.confirmed,
            "pipeline": self.pipeline,
            "pipeline_degrades": self.pipeline_degrades,
            "phases": self._phases.totals(),
            "compile_ms": dict(self.compile_ms),
        }

    def tick(self) -> None:
        """Run exactly one GGRS frame (manual-clock test pattern — the
        TimeUpdateStrategy::ManualDuration analog, tests/common/mod.rs:45-55)."""
        self.update(1.0 / self.app.fps)

    # -- per-session-type steps ---------------------------------------------

    def _step_session(self) -> Optional[List[GgrsRequest]]:
        """One session tick: returns its request list (to be flushed by the
        caller — possibly coalesced with other ticks'), or None if the tick
        produced nothing (stall, handshake, mismatch)."""
        self.ticks += 1
        telemetry.count("ticks_total", help="session ticks stepped")
        s = self.session
        if isinstance(s, SyncTestSession):
            return self._step_synctest()
        if getattr(s, "is_spectator", False):
            return self._step_spectator()
        return self._step_p2p()

    def _step_synctest(self) -> Optional[List[GgrsRequest]]:
        s = self.session
        self.local_players = list(range(s.num_players()))
        for handle, value in self.read_inputs(self.local_players).items():
            s.add_local_input(handle, value)
        try:
            with span("SessionAdvanceFrame"):
                return s.advance_frame()
        except MismatchedChecksumError as e:
            trace_log("SyncTest mismatch: %s", e)
            self._report_mismatch(e)
            if self.on_mismatch is not None:
                self.on_mismatch(e)
            return None

    def _step_p2p(self) -> Optional[List[GgrsRequest]]:
        s = self.session
        self.local_players = list(s.local_player_handles())
        if s.current_state() == SessionState.RUNNING:
            for handle, value in self.read_inputs(self.local_players).items():
                s.add_local_input(handle, value)
        try:
            with span("SessionAdvanceFrame"):
                requests = s.advance_frame()
        except PredictionThresholdError:
            trace_log("frame %d skipped: prediction threshold", self.frame)
            self.stalled_frames += 1
            telemetry.count("stalled_frames_total", help="ticks skipped on stall", kind="p2p")
            telemetry.record("stall", frame=self.frame, reason="prediction_threshold")
            return None
        except NotSynchronizedError:
            return None  # still in the sync handshake; sim time does not advance
        self._drain_events()
        return requests

    def _step_spectator(self) -> Optional[List[GgrsRequest]]:
        s = self.session
        self.local_players = []
        if s.current_state() != SessionState.RUNNING:
            return None
        try:
            return s.advance_frame()
        except PredictionThresholdError:
            trace_log("spectator frame skipped: waiting for host input")
            self.stalled_frames += 1
            telemetry.count(
                "stalled_frames_total", help="ticks skipped on stall", kind="spectator"
            )
            telemetry.record("stall", frame=self.frame, reason="waiting_for_host")
            return None
        except NotSynchronizedError:
            return None

    def _drain_events(self) -> None:
        s = self.session
        if hasattr(s, "events"):
            for ev in s.events():
                self.events.append(ev)
                if isinstance(ev, DesyncDetected):
                    self._report_desync(ev)
                if self.on_event is not None:
                    self.on_event(ev)

    def _record_network_stats(self) -> None:
        """Mirror per-peer NetworkStats into telemetry gauges plus one
        timeline event per peer (called once per host tick while enabled)."""
        s = self.session
        handles = getattr(s, "remote_handle_addr", None)
        if handles is None:
            if getattr(s, "is_spectator", False):
                behind = s.frames_behind_host()
                telemetry.gauge_set(
                    "spectator_frames_behind", behind, "spectator catchup lag"
                )
                telemetry.record("network_stats", peer="host", frames_behind=behind)
            return
        for h in sorted(handles):
            try:
                st = s.network_stats(h)
            except InvalidRequestError:
                continue  # endpoint gone (legacy raising sessions)
            if not st.is_live:
                continue  # local / spectator / disconnected handle
            telemetry.gauge_set("ping_ms", st.ping_ms, "round-trip ping", peer=h)
            telemetry.gauge_set(
                "send_queue_len", st.send_queue_len, "pending outbound inputs",
                peer=h,
            )
            telemetry.gauge_set("kbps_sent", st.kbps_sent, "outbound bandwidth", peer=h)
            telemetry.gauge_set(
                "local_frames_behind", st.local_frames_behind,
                "our frame lag vs this peer", peer=h,
            )
            telemetry.gauge_set(
                "remote_frames_behind", st.remote_frames_behind,
                "peer's frame lag vs us", peer=h,
            )
            telemetry.record(
                "network_stats", peer=h, ping_ms=st.ping_ms,
                send_queue_len=st.send_queue_len, kbps_sent=st.kbps_sent,
                local_frames_behind=st.local_frames_behind,
                remote_frames_behind=st.remote_frames_behind,
            )
        if hasattr(s, "frames_ahead"):
            telemetry.observe(
                "input_latency_frames", max(s.frames_ahead(), 0),
                "frames the session runs ahead of confirmed remote input",
            )

    def _report_mismatch(self, e: MismatchedChecksumError) -> None:
        """SyncTest mismatch: timeline event + forensics report (the report
        is written only when a forensics directory is configured)."""
        telemetry.record(
            "checksum_mismatch", source="synctest",
            frames=list(e.mismatched_frames), current_frame=e.current_frame,
        )
        telemetry.write_desync_report(
            "synctest_mismatch", reg=self.app.reg, world=self.world,
            frames=e.mismatched_frames,
        )

    def _report_desync(self, ev: DesyncDetected) -> None:
        """P2P DesyncDetected: timeline event + forensics report.

        The report carries every resolved local per-frame checksum the
        session still holds, so two peers' reports can be frame-aligned
        offline (``replay_tool.py merge-reports``)."""
        telemetry.record(
            "checksum_mismatch", source="p2p", frames=[ev.frame],
            local_checksum=ev.local_checksum,
            remote_checksum=ev.remote_checksum, addr=repr(ev.addr),
        )
        local = getattr(self.session, "_local_checksums", None) or {}
        telemetry.write_desync_report(
            "p2p_desync", reg=self.app.reg, world=self.world,
            frames=[ev.frame], local_checksum=ev.local_checksum,
            remote_checksum=ev.remote_checksum, addr=ev.addr,
            checksums={f: v for f, v in local.items() if isinstance(v, int)},
        )

    # -- request dispatch (the TPU-offload seam, SURVEY §3.6) ---------------

    def _handle_requests(self, requests: List[GgrsRequest]) -> None:
        with span("HandleRequests"):
            s = self.session
            # mirror session -> driver counters (schedule_systems.rs:195-220)
            self.ring.set_depth(self._ring_depth(s))
            self.confirmed = s.confirmed_frame()
            i = 0
            n = len(requests)
            while i < n:
                r = requests[i]
                if isinstance(r, LoadRequest):
                    if self.megastep:
                        # fuse the load into the following run's megastep
                        # dispatch when its target is device-ring resident
                        j = i + 1
                        while j < n and isinstance(
                            requests[j], (AdvanceRequest, SaveRequest)
                        ):
                            j += 1
                        self._run_megastep(r, requests[i + 1:j])
                        i = j
                    else:
                        # rollback servicing seam: the Load plus its
                        # following Advance/Save run are one unit — a
                        # verified speculation hit replaces BOTH the ring
                        # materialize and the resim with cache selects
                        j = i + 1
                        while j < n and isinstance(
                            requests[j], (AdvanceRequest, SaveRequest)
                        ):
                            j += 1
                        self._service_rollback(r, requests[i + 1:j])
                        i = j
                else:
                    j = i
                    while j < n and isinstance(
                        requests[j], (AdvanceRequest, SaveRequest)
                    ):
                        j += 1
                    if self.megastep:
                        self._run_megastep(None, requests[i:j])
                    else:
                        self._run_batch(requests[i:j])
                    i = j
            # prune AFTER processing (discard_old_snapshots): with coalesced
            # ticks, an early tick's Load can target a frame below a LATER
            # tick's confirmed frame (the session takes first_incorrect per
            # tick, then lets confirmed rise) — pruning up front would evict
            # the rollback target, the exact MissingSnapshotError shape of
            # the round-4 donation regression
            self.ring.confirm(self.confirmed)
            # fire AFTER the batch: a corrective Load/Advance in the same
            # request list must land before observers treat the frame as
            # final (a replay watermark reading final_frames() from this
            # hook would otherwise persist the mispredicted inputs)
            if self.on_confirmed is not None and self.confirmed != NULL_FRAME:
                self.on_confirmed(self.confirmed)
            # drafts for the live frame ride the idle post-tick slot: the
            # fan-out dispatch + cache bookkeeping happen after every
            # rollback in this list has been serviced (and timed)
            self._flush_speculation()

    def _flush_speculation(self) -> None:
        """Apply the cache-maintenance ops recorded during request handling.

        Deferral keeps the hedge fan-out (an M-branch, depth-deep dispatch
        plus cache bookkeeping) AND the invalidation drops (synchronous
        buffer deallocation) OFF the rollback-servicing critical path:
        ``rollback_service_ms{path=hit}`` times the rollback itself, not
        next tick's drafts or last tick's frees.  Ops replay in recorded
        order, so a mid-list correction still drops the branches an earlier
        run hedged from a superseded state.  Called before a Load's
        servicing timer starts (same-list ordering as the old inline calls)
        and at the end of ``_handle_requests``."""
        pending, self._pending_speculate = self._pending_speculate, []
        for op in pending:
            if op[0] == "inv":
                self.spec_cache.invalidate_after(op[1])
                continue
            _, src_fn, hit_handle, start, inputs = op
            if src_fn is None:
                # depth-1 full hit: the pre-advance source is the rollback
                # target itself — materialize the ring handle (one slice
                # dispatch at most; still zero resim frames)
                src = self.app.reg.load_state(materialize(hit_handle))
            else:
                src = src_fn()
            self.spec_cache.speculate(src, start, inputs)
        if pending and self.measure_rollback_service:
            # measurement mode only: retire drafts in the slot that issued
            # them so no later servicing span waits on them through device
            # serialization
            self.spec_cache.drain_drafts()

    def _note_rollback(self, frame: int, cause=None) -> None:
        """Rollback attribution shared by the host-materialize load path and
        the megastep's fused device-ring load: counters, cause blame, and
        the always-on flight-recorder entry.

        ``cause`` is the session's :class:`RollbackCause`; a cause-less
        legacy/replay load blames handle ``"unknown"`` so
        ``rollback_cause_total`` summed over handles always equals
        ``rollbacks_total``."""
        depth = self.frame - frame
        self.rollbacks += 1
        self._phases.note_rollback(depth)
        blamed = cause.handle if cause is not None else "unknown"
        if blamed is None:
            blamed = "unknown"
        lateness = cause.lateness if cause is not None else depth
        kind = cause.kind if cause is not None else "unknown"
        mismatch = bool(cause.mismatch) if cause is not None else False
        telemetry.count("rollbacks_total", help="LoadRequests executed")
        telemetry.observe(
            "rollback_depth", depth,
            "frames rolled back per LoadRequest",
        )
        telemetry.count(
            "rollback_cause_total",
            help="rollbacks attributed to the peer whose input caused them",
            handle=blamed,
        )
        telemetry.observe(
            "input_lateness_frames", lateness,
            "frames late the blamed input arrived (rollback depth it forced)",
            handle=blamed,
        )
        telemetry.record("rollback", to_frame=frame, from_frame=self.frame,
                         depth=depth, handle=blamed, lateness=lateness,
                         mismatch=mismatch, cause_kind=kind)
        fr = telemetry.flight_recorder()
        if fr.enabled:
            # the always-on ring gets the attributed entry too, so a desync
            # report's flight_record section names the blamed peer even when
            # the metrics registry was off
            fr.record("rollback", to_frame=frame, from_frame=self.frame,
                      depth=depth, handle=blamed, lateness=lateness,
                      mismatch=mismatch, cause_kind=kind)

    def _load(self, frame: int, cause=None) -> None:
        """LoadGameState: restore the ring snapshot for ``frame``
        (schedule_systems.rs:238-249).

        ``cause`` is the session's :class:`RollbackCause` attribution; when
        a legacy/replay path supplies none the rollback is attributed to
        handle ``"unknown"`` so ``rollback_cause_total`` summed over handles
        always equals ``rollbacks_total``."""
        self._note_rollback(frame, cause)
        with self._phases.phase("rollback_load"), span("LoadWorld"):
            stored, checksum = self.ring.rollback(frame)
            was_lazy = isinstance(stored, LazySlice)
            if (
                self.pipeline
                and was_lazy
                and self._last_stacked is not None
                and stored._stacked is self._last_stacked
            ):
                # the Load targets the most recent dispatch's stacked output:
                # the materialize below carries an XLA data dependency on that
                # dispatch, so the one-deep window degrades to the synchronous
                # shape for this tick (correct by construction; counted so the
                # degradation rate is observable)
                self.pipeline_degrades += 1
                telemetry.count(
                    "pipeline_degrade_total",
                    help="loads targeting the in-flight dispatch's output "
                         "(pipeline degraded to synchronous for that tick)",
                )
            self.world = self.app.reg.load_state(materialize(stored))
            self._world_checksum = checksum
            self.frame = frame
        # LazySlice materialization / non-identity decode produce fresh
        # buffers; a materialized identity snapshot IS the ring's object
        self._world_donatable = (
            was_lazy or not self.app.reg.is_identity_strategy()
        )
        self._last_stacked = None
        self._last_stacked_frame = None
        if self.spec_cache is not None:
            # branches hedged from now-superseded predicted states must not
            # serve future lookups (see SpeculationCache.invalidate_after);
            # the drop (buffer deallocation) is deferred to the flush seam so
            # it stays off the timed servicing path — _flush_speculation runs
            # before any later lookup can observe the stale entries
            self._pending_speculate.append(("inv", frame))

    def _service_rollback(self, load: LoadRequest, run: List[GgrsRequest]) -> None:
        """Service one LoadRequest plus its following Advance/Save run.

        The speculation cache is consulted FIRST: a verified hit (the
        corrected input sequence was hedged last tick) services the rollback
        entirely from cached branch states — the ring pop is bookkeeping
        only (the megastep fused-load pattern), the restored state and every
        resaved frame are device-side selects, and zero frames resimulate.
        A miss falls back to the existing materialize + resim path.  Both
        paths feed the ``rollback_service_ms{path=hit|miss}`` histogram —
        the number the bench's >=5x hit-path gate reads."""
        # issue any drafts recorded by an earlier run in this coalesced
        # request list BEFORE the load (and before the timer): the hedge
        # must precede the correction exactly as it did when speculate()
        # fired inline, so invalidate_after can drop superseded branches
        self._flush_speculation()
        if self.measure_rollback_service:
            import jax

            # bgt: ignore[BGT010, BGT011]: deliberate — measurement mode
            # only (bench.py _speculation_service_arm): retire the PIPELINED
            # BACKLOG (previous ticks' advance + draft dispatches) before
            # the timer starts, so the span times this rollback's servicing
            # and not whatever was already in flight
            jax.block_until_ready(self.world.comps)
        t0 = time.perf_counter()
        adv = [r for r in run if isinstance(r, AdvanceRequest)]
        got = None
        if self.spec_cache is not None and adv:
            got = self.spec_cache.lookup_seq(
                load.frame, np.stack([a.inputs for a in adv])
            )
            telemetry.count(
                "speculation_hits_total" if got is not None
                else "speculation_misses_total",
                help="speculative branch-cache lookups",
            )
        if got is not None:
            self._note_rollback(load.frame, load.cause)
            with self._phases.phase("rollback_load"), span("LoadWorld"):
                # bookkeeping-only rollback: pop the ring entries above the
                # target and keep the stored handle — no materialize, no
                # load_state; the world restore is the cache select inside
                # _run_batch (O(1) in rollback depth)
                stored, checksum = self.ring.rollback(load.frame)
                self.frame = load.frame
            self._pending_speculate.append(("inv", load.frame))
            self._last_stacked = None
            self._last_stacked_frame = None
            self._world_donatable = False
            telemetry.record(
                "speculation_hit", frame=load.frame, depth=got[0],
                advances=len(adv),
            )
            self._run_batch(run, hit=got, hit_pre=(stored, checksum))
        else:
            self._load(load.frame, load.cause)
            self._run_batch(run)
        if self.measure_rollback_service:
            import jax

            # bgt: ignore[BGT010, BGT011]: deliberate — measurement mode
            # only (bench.py stage_speculation): retire the servicing work
            # inside the timed span so hit and miss p99 compare the same
            # thing
            jax.block_until_ready(self.world.comps)
        telemetry.observe(
            "rollback_service_ms", (time.perf_counter() - t0) * 1e3,
            "wall ms to service one rollback (LoadRequest + its following "
            "Advance/Save run)",
            buckets=telemetry.LATENCY_MS_BUCKETS,
            path="hit" if got is not None else "miss",
        )

    def _stage_rows(self, adv: List[AdvanceRequest]):
        """Fill the persistent pinned input/status buffers in place and
        return ``[k, ...]`` views — the BatchedRunner staging pattern ported
        to the solo runner, so steady-state ticks allocate nothing on host.
        Views of the capacity buffer have exactly the shapes ``np.stack``
        produced, so the jit cache sees no new variants."""
        k = len(adv)
        row_in = np.asarray(adv[0].inputs)
        row_st = np.asarray(adv[0].status)
        if (
            self._stage_inputs is None
            or self._stage_cap < k
            or self._stage_inputs.shape[1:] != row_in.shape
            or self._stage_inputs.dtype != row_in.dtype
            or self._stage_status.shape[1:] != row_st.shape
            or self._stage_status.dtype != row_st.dtype
        ):
            self._stage_cap = max(k, self._stage_cap * 2)
            self._stage_inputs = np.zeros(
                (self._stage_cap, *row_in.shape), row_in.dtype
            )
            self._stage_status = np.zeros(
                (self._stage_cap, *row_st.shape), row_st.dtype
            )
            telemetry.devmem.note(
                self._devmem_tag + "/staging",
                self._stage_inputs.nbytes + self._stage_status.nbytes,
            )
        from .utils import staging

        san = staging.sanitizer()
        san.guard_write(self._stage_inputs, "runner._stage_rows/inputs")
        san.guard_write(self._stage_status, "runner._stage_rows/status")
        for i, a in enumerate(adv):
            self._stage_inputs[i] = a.inputs
            self._stage_status[i] = a.status
        # the buffers are rewritten next tick: commit synchronously so the
        # in-flight upload can never read the next tick's bytes
        commit = staging.commit
        return commit(self._stage_inputs[:k]), commit(self._stage_status[:k])

    def _stage_packed_rows(self, adv: List[AdvanceRequest], start_frame: int,
                           k_pad: Optional[int] = None,
                           has_load: int = 0, load_slot: int = 0):
        """Pack a run's advances into the persistent single-upload buffer
        (ops/packing.py) and return the ``[k_pad + 1, W]`` view: prefix row
        (frame / n_real / load words) + one payload row per frame.  The
        fixed-shape programs (canonical, megastep) pass ``k_pad > k``;
        padded rows repeat the last real row and are masked by ``n_real``."""
        from .ops.packing import pack_prefix, pack_row, repeat_last_row

        spec = self.app.packed_spec
        k = len(adv)
        kp = k_pad if k_pad is not None else k
        if self.input_queue:
            # device-resident input queue: rotate depth-2 staging buffers so
            # the upload overlaps the next tick's host work (StagingQueue)
            from .utils.staging import StagingQueue

            if self._packed_queue is None or self._packed_cap < kp:
                self._packed_cap = max(kp, self._packed_cap * 2)
                cap = self._packed_cap
                self._packed_queue = StagingQueue(lambda: spec.new_buffer(cap))
                telemetry.devmem.note(
                    self._devmem_tag + "/packed_staging",
                    self._packed_queue.nbytes,
                )
            buf = self._packed_queue.acquire()
        else:
            if self._stage_packed is None or self._packed_cap < kp:
                self._packed_cap = max(kp, self._packed_cap * 2)
                self._stage_packed = spec.new_buffer(self._packed_cap)
                telemetry.devmem.note(
                    self._devmem_tag + "/packed_staging",
                    self._stage_packed.nbytes,
                )
            buf = self._stage_packed
        pack_prefix(buf, start_frame, k, has_load, load_slot)
        for i, a in enumerate(adv):
            pack_row(spec, buf, i, a.inputs, a.status)
        repeat_last_row(buf, k, kp)
        if self.input_queue:
            # non-blocking start: the queue blocks (if ever) at the matching
            # acquire(), two ticks from now
            return self._packed_queue.commit(buf[:kp + 1])
        # commit synchronously: the buffer is rewritten next dispatch and
        # the upload itself is asynchronous (see utils/staging.py)
        from .utils.staging import commit

        return commit(buf[:kp + 1])

    def _note_dispatch_uploads(self, n: int, packed_buf=None) -> None:
        """Upload census: ``n`` host->device uploads rode this dispatch
        (always-on ints + the pre-bound telemetry family)."""
        self.host_uploads += n
        self._m_uploads.observe(n)
        if packed_buf is not None:
            self.packed_upload_bytes += packed_buf.nbytes
            self._m_packed_bytes.inc(packed_buf.nbytes)

    def _run_batch(self, run: List[GgrsRequest], hit=None, hit_pre=None) -> None:
        """Execute a maximal Advance/Save run as one fused device call.

        ``hit``/``hit_pre`` come from :meth:`_service_rollback` when the
        rollback's corrected input sequence was hedged: ``hit`` is the
        ``lookup_seq`` result serving the first ``skip`` advances as cache
        selects (a fully-hedged rollback dispatches NO resim at all) and
        ``hit_pre`` is the ``(stored_handle, checksum)`` the ring pop
        returned for the rollback target — the pre-run state, needed for
        defensive leading saves and depth-1 re-speculation.  With
        speculation enabled the live frame's predicted transition fans out
        candidate branches for the next tick either way."""
        adv = [r for r in run if isinstance(r, AdvanceRequest)]
        k = len(adv)
        ph = self._phases
        ph.note_advances(k)
        identity = self.app.reg.is_identity_strategy()
        if not hasattr(self._world_checksum, "to_int"):
            # tolerate external writes of a bare uint32[2] device checksum
            self._world_checksum = wrap_single_checksum(self._world_checksum)
        pre_world, pre_checksum = self.world, self._world_checksum
        pre_frame = self.frame
        if self.on_advance is not None:
            for i, a in enumerate(adv):
                self.on_advance(frame_add(pre_frame, i + 1), a.inputs, a.status)
        stacked = None
        batch_checks = None  # BatchChecks over this dispatch's stacked checksums
        skip = 0
        cache_states = cache_bc = None
        hit_handle = hit_checksum = None
        if hit is not None:
            # rollback served from the speculation cache (_service_rollback
            # already popped the ring and set self.frame to the target):
            # state, checksum and frame advance are device-side selects of
            # the verified branch — zero resim frames for the served prefix
            skip, cache_states, cache_checks = hit
            cache_bc = BatchChecks(cache_checks)
            self.world = cache_states(skip - 1)
            self._world_checksum = cache_bc.ref(skip - 1)
            self.frame = frame_add(self.frame, skip)
            self.cache_served_frames += skip
            telemetry.count(
                "cache_served_frames_total", skip,
                help="rollback frames served from the speculation cache "
                     "instead of resimulated",
            )
            hit_handle, hit_checksum = hit_pre
        # state feeding the LAST advance (used to speculate the next tick),
        # as a THUNK — slicing it out of a stacked buffer is a device
        # dispatch, so resolution is deferred to _flush_speculation, off the
        # timed servicing path.  With a full cache hit (skip == k)
        # self.world is already the POST-advance state: the pre-advance
        # source is the previous cached frame, or for a single served
        # advance the rollback target itself (resolved from the ring handle
        # at the flush — speculating from the post-advance state would
        # double-advance the hedge branches, states one frame ahead of
        # their labels)
        last_adv_src = (lambda w=self.world: w)
        if skip == k:
            last_adv_src = (
                (lambda cs=cache_states, i=skip - 2: cs(i))
                if skip >= 2 else None
            )
        use_branched = (
            self.spec_cache is not None and self.app.canonical_branches is not None
        )
        # packed single-upload dispatch (the default): one int8 buffer
        # replaces the inputs/status/frame upload triple.  The branched
        # program keeps its own [B, K] shape (app.packed_resim_fn is None
        # under canonical_branches, so self.packed is already False there).
        use_packed = self.packed and not use_branched
        # Donation decision + pre-resolution of leading (c==0) saves.  A
        # leading save stores the PRE-dispatch state; donation kills that
        # buffer, so it must be serviceable without pre_world: identity
        # strategies slice it out of the PREVIOUS dispatch's stacked saves
        # (bit-identical: final == stacked[-1]); lossy strategies encode it
        # before the dispatch runs.
        leading_saves = []
        for r in run:
            if isinstance(r, AdvanceRequest):
                break
            if isinstance(r, SaveRequest):
                leading_saves.append(r)
        c0_stored = None
        donate = (
            self.enable_donation
            and self.spec_cache is None
            and self._world_donatable
            and k - skip > 0
            and not use_branched
            and (
                self.app.packed_resim_fn_donated if use_packed
                else self.app.resim_fn_donated
            ) is not None
        )
        if donate and leading_saves:
            if identity:
                if self._last_stacked is not None and all(
                    r.frame == self._last_stacked_frame for r in leading_saves
                ):
                    c0_stored = LazySlice(self._last_stacked, self._last_k - 1)
                else:
                    donate = False  # must ring pre_world itself
            else:
                c0_stored = self.app.reg.store_state(materialize(pre_world))
        if k - skip > 0:
            self.device_dispatches += 1
            self.rollback_frames += max(k - skip - 1, 0)
            telemetry.count("device_dispatches_total", help="fused resim dispatches")
            telemetry.count(
                "resim_frames_total", max(k - skip - 1, 0),
                help="frames resimulated beyond the first of each dispatch",
            )
            if donate:
                telemetry.count(
                    "donated_dispatches_total", help="dispatches donating the input world"
                )
            with span("AdvanceWorld"):
                pk = None
                if use_packed:
                    # fixed-shape canonical programs take a canonical_depth-
                    # deep buffer with the real count in the prefix; the
                    # per-k programs take exactly [k+1, W]
                    K = self.app.canonical_depth
                    if K is not None and k - skip > K:
                        raise ValueError(
                            f"resim depth {k - skip} exceeds canonical_depth "
                            f"{K}; raise App(canonical_depth=...) above "
                            "every session window"
                        )
                    with ph.phase("stage_inputs"):
                        pk = self._stage_packed_rows(
                            adv[skip:], self.frame, k_pad=K
                        )
                else:
                    with ph.phase("stage_inputs"):
                        inputs, status = self._stage_rows(adv[skip:])
                variant = (
                    "branched" if use_branched
                    else (("packed_" if use_packed else "")
                          + ("donated" if donate else "plain")),
                    k - skip,
                )
                fresh = variant not in self._seen_variants
                t_build = time.perf_counter() if fresh else 0.0
                with ph.phase("wave_dispatch"):
                    if use_branched:
                        final, stacked, checks = self._dispatch_branched(
                            inputs, status, adv[-1]
                        )
                        self._note_dispatch_uploads(4)
                    elif use_packed:
                        fn = (
                            self.app.packed_resim_fn_donated if donate
                            else self.app.packed_resim_fn
                        )
                        if donate:
                            self.donated_dispatches += 1
                        final, stacked, checks = fn(self.world, pk)
                        self._note_dispatch_uploads(1, pk)
                    else:
                        fn = (
                            self.app.resim_fn_donated if donate
                            else self.app.resim_fn
                        )
                        if donate:
                            self.donated_dispatches += 1
                        final, stacked, checks = fn(
                            self.world, inputs, status, self.frame
                        )
                        self._note_dispatch_uploads(3)
                    batch_checks = BatchChecks(checks)
                    if self.pipeline:
                        # ahead-of-tick readback: the device->host checksum
                        # copy rides behind the dispatch; harvest() collects
                        # it next tick while the device runs frame N+1
                        self._rbq.start(batch_checks)
                if fresh:
                    self._note_compile(variant, time.perf_counter() - t_build)
                if self.spec_cache is not None and k - skip >= 2:
                    last_adv_src = (
                        lambda s=stacked, i=k - skip - 2: slice_frame(s, i)
                    )
                self.world = final
                self._world_checksum = batch_checks.ref(k - skip - 1)
                self.frame = frame_add(self.frame, k - skip)
                self._last_stacked = stacked
                self._last_k = k - skip
                self._last_stacked_frame = self.frame
                self._world_donatable = True  # final is a fresh buffer
        materialize_saves = False
        if stacked is not None:
            stacked_bytes = self._stacked_bytes_by_k.get(k - skip)
            if stacked_bytes is None:
                from .utils.mem import tree_device_bytes

                stacked_bytes = tree_device_bytes(stacked)
                self._stacked_bytes_by_k[k - skip] = stacked_bytes
            materialize_saves = stacked_bytes > self.ring_materialize_bytes
            telemetry.gauge_set(
                "save_bytes", stacked_bytes,
                "device bytes of the last dispatch's stacked save buffer",
            )
            telemetry.record(
                "dispatch", frame=self.frame, advances=k - skip, skipped=skip,
                donated=donate, save_bytes=stacked_bytes,
            )
        pushed_pre_world = False
        with ph.phase("store_save"), span("SaveWorld"):
            c = 0  # advances seen so far within the run
            for r in run:
                if isinstance(r, AdvanceRequest):
                    c += 1
                    continue
                if c == 0:
                    if hit is not None:
                        # leading save after a cache-served rollback: the
                        # live world predates the target, but the ring pop
                        # already handed us the target's stored form —
                        # re-push it (the megastep loaded_pair pattern)
                        self.ring.push(r.frame, (hit_handle, hit_checksum))
                        r.cell.save(r.frame, hit_checksum)
                        continue
                    if c0_stored is not None:
                        # pre-resolved (donation path): pre_world's buffers
                        # may already be dead — serve from the previous
                        # dispatch's stacked saves / the pre-encoded store
                        self.ring.push(r.frame, (c0_stored, pre_checksum))
                        # the ref itself is the provider: callable (forcing)
                        # with a non-blocking peek() for the pipelined path
                        r.cell.save(r.frame, pre_checksum)
                        continue
                    state_s, cs = pre_world, pre_checksum
                    pushed_pre_world = identity
                elif c <= skip:
                    # cache-served frame: store a lazy handle into the
                    # branch's stacked states (alias — the cache entry keeps
                    # the buffer alive anyway); slicing dispatches only on a
                    # later rollback, keeping hit servicing at O(1) dispatches
                    state_s = LazySlice(cache_states.stacked, c - 1)
                    if materialize_saves:
                        state_s = state_s.materialize()
                    cs = cache_bc.ref(c - 1)
                else:
                    # defer the per-frame slice: the ring stores a handle into
                    # the stacked buffer; slicing dispatches only on rollback
                    # (or eagerly for big worlds — see ring_materialize_bytes)
                    state_s = LazySlice(stacked, c - 1 - skip)
                    if materialize_saves:
                        state_s = state_s.materialize()
                    cs = batch_checks.ref(c - 1 - skip)
                stored = (
                    state_s
                    if identity
                    else self.app.reg.store_state(materialize(state_s))
                )
                self.ring.push(r.frame, (stored, cs))
                r.cell.save(r.frame, cs)
        if pushed_pre_world and self._world is pre_world:
            # save-only run (or full cache skip): the ring now aliases the
            # live world object; the next dispatch must not donate it
            self._world_donatable = False
        if (
            materialize_saves
            or self.spec_cache is not None
            or not self.enable_donation
            or not identity
        ):
            # retaining the stacked buffer only pays off when the NEXT
            # dispatch's leading save can be served from it (identity +
            # donation); otherwise it would just pin k extra world copies
            # in device memory — exactly what ring_materialize_bytes bounds
            self._last_stacked = None
            self._last_stacked_frame = None
        # hedge the live frame: if its inputs were (partly) predicted, fan out
        # candidate branches for the same transition (the branched program
        # already did this inside its own dispatch)
        if (
            not (self.spec_cache is not None and self.app.canonical_branches)
            and self.spec_cache is not None
            and k > 0
            and np.any(adv[-1].status == InputStatus.PREDICTED)
        ):
            # record the hedge only; _flush_speculation issues the draft
            # fan-out at the next seam (before a following Load's timer, or
            # at the tick boundary) so drafts ride the otherwise-idle slot
            # instead of the rollback-servicing critical path
            self._pending_speculate.append(
                ("spec", last_adv_src, hit_handle,
                 frame_add(self.frame, -1), adv[-1].inputs)
            )

    # -- device-resident megastep (ops/megastep.py) -------------------------

    def _ensure_megastep(self) -> None:
        """Lazily build the megastep program + device ring for the current
        session (depth formulas mirror ``_ring_depth``/``set_session``: one
        fixed-shape program per session, so every flush runs the same
        machine code)."""
        if self._ms_fn is not None:
            return
        from .ops.megastep import init_device_ring, make_megastep_fn

        s = self.session
        mp = s.max_prediction()
        window = (
            s.rollback_window() if hasattr(s, "rollback_window") else mp
        )
        # deepest session-shaped run: a rollback landing in the same
        # coalesced flush as catch-up ticks (the canonical-depth bound in
        # set_session)
        self._ms_k = self.coalesce_frames + max(window, mp)
        # one more slot than the host ring so k_max < R: within a single
        # dispatch no two written frames share a slot (a duplicate scatter
        # index would make the writeback order-dependent)
        self._ms_slots = self._ring_depth(s) + 1
        app = self.app
        self._ms_fn = make_megastep_fn(
            app.reg, app.step, app.packed_spec, app.fps, seed=app.seed,
            retention=app.retention, k_max=self._ms_k,
            ring_slots=self._ms_slots,
        )
        self._ms_ring, self._ms_ring_frames = init_device_ring(
            self.world, self._ms_slots
        )
        self._dev_frames = {}
        # device-memory accounting: the on-device ring is a fixed
        # [slots, ...] stacked world plus the slot->frame vector
        from .utils.mem import tree_device_bytes

        telemetry.devmem.note(
            self._devmem_tag + "/megastep_ring",
            tree_device_bytes(self._ms_ring)
            + tree_device_bytes(self._ms_ring_frames),
        )

    def _dev_slot(self, frame: int) -> Optional[int]:
        """Device-ring slot currently holding ``frame``, or None when the
        frame was overwritten / never written.  The host mirror makes the
        check exact: a miss degrades to the host materialize path, never to
        a wrong row.  Python ``%`` is non-negative like jnp's (divisor-sign)
        ``%``, so wrapped int32 frames map to the same slot on both sides."""
        slot = frame % self._ms_slots
        return slot if self._dev_frames.get(slot) == frame else None

    def _run_megastep(
        self, load: Optional[LoadRequest], run: List[GgrsRequest]
    ) -> None:
        """Megastep flush: an optional LoadRequest plus its following
        Advance/Save run as ONE device dispatch fed by ONE packed upload —
        including the rollback itself, when its target frame is still
        resident in the on-device snapshot ring (ops/megastep.py)."""
        self._ensure_megastep()
        ph = self._phases
        n_adv = sum(1 for r in run if isinstance(r, AdvanceRequest))
        has_load = 0
        load_slot = 0
        loaded_pair = None
        if load is not None:
            slot = self._dev_slot(load.frame) if n_adv > 0 else None
            if slot is None:
                # ring miss (or a load with nothing to replay): host
                # materialize path — bit-identical, one extra dispatch
                self._load(load.frame, load.cause)
            else:
                self._note_rollback(load.frame, load.cause)
                with ph.phase("rollback_load"), span("LoadWorld"):
                    # bookkeeping only: pop newer host-ring entries and take
                    # the checksum handle; the STATE restore happens inside
                    # the megastep dispatch (no materialize, no extra
                    # dispatch, no host sync)
                    stored, checksum = self.ring.rollback(load.frame)
                    loaded_pair = (stored, checksum)
                    self._world_checksum = checksum
                    self.frame = load.frame
                self.fused_ring_loads += 1
                telemetry.count(
                    "fused_ring_loads_total",
                    help="rollback loads served from the device ring inside "
                         "the megastep dispatch",
                )
                has_load = 1
                load_slot = slot
                self._last_stacked = None
                self._last_stacked_frame = None
        if not run:
            return
        # chunk the run so each dispatch carries at most k_max advances
        # (session-shaped runs always fit — k_max covers a maximal rollback
        # + coalesced catch-up — but replay/tool request lists can be longer)
        i, n = 0, len(run)
        while i < n:
            j, c = i, 0
            while j < n:
                if isinstance(run[j], AdvanceRequest):
                    if c == self._ms_k:
                        break
                    c += 1
                j += 1
            self._megastep_chunk(run[i:j], has_load, load_slot, loaded_pair)
            has_load, load_slot, loaded_pair = 0, 0, None
            i = j

    def _megastep_chunk(
        self, run: List[GgrsRequest], has_load: int, load_slot: int,
        loaded_pair,
    ) -> None:
        """One megastep dispatch: <= k_max advances (+ interleaved saves),
        optionally consuming a fused device-ring load in the same program."""
        ph = self._phases
        adv = [r for r in run if isinstance(r, AdvanceRequest)]
        k = len(adv)
        ph.note_advances(k)
        if not hasattr(self._world_checksum, "to_int"):
            self._world_checksum = wrap_single_checksum(self._world_checksum)
        pre_world, pre_checksum = self.world, self._world_checksum
        pre_frame = self.frame
        if self.on_advance is not None:
            for i, a in enumerate(adv):
                self.on_advance(frame_add(pre_frame, i + 1), a.inputs, a.status)
        stacked = None
        batch_checks = None
        if k > 0:
            self.device_dispatches += 1
            self.megastep_dispatches += 1
            self.rollback_frames += max(k - 1, 0)
            telemetry.count("device_dispatches_total", help="fused resim dispatches")
            telemetry.count(
                "resim_frames_total", max(k - 1, 0),
                help="frames resimulated beyond the first of each dispatch",
            )
            with span("AdvanceWorld"):
                with ph.phase("stage_inputs"):
                    pk = self._stage_packed_rows(
                        adv, self.frame, k_pad=self._ms_k,
                        has_load=has_load, load_slot=load_slot,
                    )
                variant = ("megastep", self._ms_k)
                fresh = variant not in self._seen_variants
                t_build = time.perf_counter() if fresh else 0.0
                with ph.phase("wave_dispatch"):
                    (final, self._ms_ring, self._ms_ring_frames, stacked,
                     checks) = self._ms_fn(
                        self.world, self._ms_ring, self._ms_ring_frames, pk
                    )
                    self._note_dispatch_uploads(1, pk)
                    batch_checks = BatchChecks(checks)
                    if self.pipeline:
                        self._rbq.start(batch_checks)
                if fresh:
                    self._note_compile(variant, time.perf_counter() - t_build)
                # host mirror of the device ring writeback (slot -> frame)
                R = self._ms_slots
                for i in range(k):
                    f = frame_add(self.frame, i + 1)
                    self._dev_frames[f % R] = f
                self.world = final
                self._world_checksum = batch_checks.ref(k - 1)
                self.frame = frame_add(self.frame, k)
        materialize_saves = False
        if stacked is not None:
            key = ("megastep", self._ms_k)
            stacked_bytes = self._stacked_bytes_by_k.get(key)
            if stacked_bytes is None:
                from .utils.mem import tree_device_bytes

                stacked_bytes = tree_device_bytes(stacked)
                self._stacked_bytes_by_k[key] = stacked_bytes
            materialize_saves = stacked_bytes > self.ring_materialize_bytes
            telemetry.gauge_set(
                "save_bytes", stacked_bytes,
                "device bytes of the last dispatch's stacked save buffer",
            )
            telemetry.record(
                "dispatch", frame=self.frame, advances=k, skipped=0,
                donated=False, save_bytes=stacked_bytes, megastep=True,
            )
        with ph.phase("store_save"), span("SaveWorld"):
            c = 0  # advances seen so far within the run
            for r in run:
                if isinstance(r, AdvanceRequest):
                    c += 1
                    continue
                if c == 0:
                    if loaded_pair is not None:
                        # leading save after a fused ring load: self.world
                        # was NOT updated host-side (the device selected the
                        # ring row), so re-push the rollback's own handle —
                        # the exact state/checksum the host path would store
                        state_s, cs = loaded_pair
                    else:
                        state_s, cs = pre_world, pre_checksum
                        if self._world is pre_world:
                            # ring aliases the live world (donation is
                            # already off in megastep mode; kept for parity)
                            self._world_donatable = False
                else:
                    # megastep requires identity strategies (ctor), so the
                    # lazy stacked-row handle IS the stored representation
                    state_s = LazySlice(stacked, c - 1)
                    if materialize_saves:
                        state_s = state_s.materialize()
                    cs = batch_checks.ref(c - 1)
                self.ring.push(r.frame, (state_s, cs))
                r.cell.save(r.frame, cs)

    def _note_compile(self, variant, dt: float) -> None:
        """Record a program variant's first-dispatch wall time (trace +
        compile dominate the first call of each ``(kind, depth)`` jit
        variant — later calls hit the executable cache), into
        :attr:`compile_ms`, the flight recorder and (when telemetry is on)
        the ``program_compile_ms`` histogram."""
        kind, depth = variant
        self._seen_variants.add(variant)
        ms = dt * 1e3
        self.compile_ms[f"{kind}_k{depth}"] = round(ms, 3)
        telemetry.flight_recorder().record(
            "compile", owner="solo", program=kind, k=depth, ms=round(ms, 3)
        )
        telemetry.observe(
            "program_compile_ms", ms,
            "wall ms of each program variant's first dispatch (trace+compile)",
            buckets=telemetry.LATENCY_MS_BUCKETS,
            owner="solo", kind=kind,
        )
        compile_guard.notify("solo", kind, ms)

    def arm_compile_guard(self) -> bool:
        """Declare warmup over: with ``BGT_COMPILE_GUARD=1`` (or
        :func:`~bevy_ggrs_tpu.utils.compile_guard.set_compile_guard`) any
        later program compile raises
        :class:`~bevy_ggrs_tpu.utils.compile_guard.RecompileError` naming
        the owner/kind and bumps ``recompiles_steady_total{owner}``.
        Returns True when armed; no-op (False) when the guard is off."""
        return compile_guard.guard().arm()

    def _dispatch_branched(self, inputs, status, last_adv):
        """One canonical [B, K] dispatch: lane 0 = the real batch; hedge
        lanes replay the real prefix then hold a candidate input from the
        last transition onward (cache entries come out of the same program
        every peer runs — bit-determinism preserved)."""
        import jax as _jax

        app = self.app
        B, K = app.canonical_branches, app.canonical_depth
        k = inputs.shape[0]
        if k > K:
            raise ValueError(f"resim depth {k} exceeds canonical_depth {K}")
        from .ops.resim import pad_repeat_last

        pad = K - k
        inputs_p = pad_repeat_last(np.asarray(inputs), pad)
        status_p = pad_repeat_last(np.asarray(status), pad)
        ib = np.broadcast_to(inputs_p[None], (B, *inputs_p.shape)).copy()
        sb = np.broadcast_to(status_p[None], (B, *status_p.shape)).copy()
        n_real = np.full((B,), k, np.int32)
        hedging = bool(np.any(last_adv.status == InputStatus.PREDICTED))
        cands = None
        if hedging:
            cands = np.asarray(
                self.spec_cache.config.candidates_fn(last_adv.inputs),
                app.input_dtype,
            )[: B - 1]
            for b in range(cands.shape[0]):
                ib[1 + b, k - 1:] = cands[b]  # real prefix, candidate held
                sb[1 + b, k - 1:] = 0  # hedges evaluate as confirmed
                n_real[1 + b] = K
        finals, stacked, checks = app.branched_fn(
            self.world, ib, sb, self.frame, n_real
        )
        if hedging and cands is not None and cands.shape[0] > 0:
            m = cands.shape[0]
            hedge_stacked = _jax.tree.map(lambda a: a[1:1 + m], stacked)
            self.spec_cache.fill_from_branched(
                frame_add(self.frame, k - 1), cands,
                hedge_stacked, checks[1:1 + m],
                offset=k - 1, depth_eff=K - (k - 1),
            )
        from .ops.resim import trim_frames
        from .snapshot.lazy import tree_index

        final0, (stacked0, checks0) = tree_index(
            (finals, trim_frames((stacked, checks), k, axis=1)), 0
        )
        return final0, stacked0, checks0
