"""bevy_ggrs_tpu — a TPU-native rollback-netcode framework.

GGPO-style P2P rollback networking for deterministic simulations, with the
capability surface of the ``bevy_ggrs`` + ``ggrs`` stack (see SURVEY.md) but
designed TPU-first: simulation state is columnar SoA arrays on device, a
rollback of N frames executes as one ``jit(lax.scan(step))`` call, speculative
remote-input branches fan out under ``vmap``, and checksums are deterministic
integer array reductions.  The session/network layer (input queues,
prediction, sync/quality/desync protocol, UDP transport) runs host-side with
a native C++ core.
"""

from .app import App, DEFAULT_FPS
from .runner import GgrsRunner
from .batch_runner import BatchedRunner
from .ops.resim import StepCtx, select_branch, slice_frame
from .ops.speculation import SpeculationConfig, SpeculationCache, pad_candidates
from .ops.variant_probe import probe_program_variants, VariantProbeReport
from .session import (
    SyncTestSession,
    P2PSession,
    SpectatorSession,
    SessionBuilder,
    UdpNonBlockingSocket,
    TcpNonBlockingSocket,
    RoomServer,
    RoomSocket,
    assign_handles,
    wait_for_players,
    InputStatus,
    SessionState,
    PlayerType,
    Player,
    DesyncDetection,
    GgrsError,
    PredictionThresholdError,
    MismatchedChecksumError,
    NotSynchronizedError,
    InvalidRequestError,
    NetworkStats,
)
from .snapshot import (
    Registry,
    WorldState,
    SnapshotRing,
    MissingSnapshotError,
    Strategy,
    CopyStrategy,
    CloneStrategy,
    ReflectStrategy,
    QuantizeStrategy,
    active_mask,
    active_count,
    spawn,
    spawn_many,
    despawn,
    despawn_where,
    despawn_recursive,
    insert_component,
    remove_component,
    insert_resource,
    remove_resource,
    world_checksum,
    checksum_to_int,
)
from .utils.frames import NULL_FRAME

__version__ = "0.1.0"
