"""Platform selection helper for CLIs and tests.

Some environments pre-import jax with a platform pinned via sitecustomize,
making JAX_PLATFORMS ineffective; ``apply_platform_env()`` applies the
``BGT_PLATFORM`` env var (e.g. ``cpu``) through jax.config instead, plus an
optional ``BGT_CPU_DEVICES`` virtual device count.  Called at the top of
every example CLI so they are runnable anywhere (see docs/tpu_notes.md §4).

``JAX_PLATFORMS`` is honored as an alias for ``BGT_PLATFORM`` (lower
precedence): an operator exporting the standard jax spelling must get the
same protection, because in the sitecustomize environments above the env
var alone is ineffective — NOTES.md round 5 records a 25-minute wedge where
``JAX_PLATFORMS=cpu`` was set but a driver_bench subprocess applying only
``BGT_*`` vars still tried to claim the dead TPU tunnel.  Fleet workers and
bench stage subprocesses inherit whichever spelling the parent used."""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    """Apply BGT_PLATFORM (or its JAX_PLATFORMS alias) / BGT_CPU_DEVICES
    through jax.config."""
    platform = (os.environ.get("BGT_PLATFORM")
                or os.environ.get("JAX_PLATFORMS"))
    ndev = os.environ.get("BGT_CPU_DEVICES")
    if not platform and not ndev:
        return
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if ndev:
        try:
            jax.config.update("jax_num_cpu_devices", int(ndev))
        except AttributeError:
            # jax < 0.5 has no jax_num_cpu_devices; the XLA flag is the
            # portable spelling, read at backend init (first device use),
            # so it still applies as long as no device has been queried
            flag = "--xla_force_host_platform_device_count"
            if flag not in os.environ.get("XLA_FLAGS", ""):
                os.environ["XLA_FLAGS"] = (
                    os.environ.get("XLA_FLAGS", "") + f" {flag}={int(ndev)}"
                ).strip()
