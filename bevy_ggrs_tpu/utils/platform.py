"""Platform selection helper for CLIs and tests.

Some environments pre-import jax with a platform pinned via sitecustomize,
making JAX_PLATFORMS ineffective; ``apply_platform_env()`` applies the
``BGT_PLATFORM`` env var (e.g. ``cpu``) through jax.config instead, plus an
optional ``BGT_CPU_DEVICES`` virtual device count.  Called at the top of
every example CLI so they are runnable anywhere (see docs/tpu_notes.md §4)."""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    """Apply BGT_PLATFORM / BGT_CPU_DEVICES through jax.config."""
    platform = os.environ.get("BGT_PLATFORM")
    ndev = os.environ.get("BGT_CPU_DEVICES")
    if not platform and not ndev:
        return
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if ndev:
        jax.config.update("jax_num_cpu_devices", int(ndev))
