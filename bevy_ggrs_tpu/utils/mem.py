"""Device-memory accounting helpers."""

from __future__ import annotations


def tree_device_bytes(tree) -> int:
    """Total bytes of every array leaf in a pytree (device or host)."""
    import jax

    return sum(a.size * a.dtype.itemsize for a in jax.tree.leaves(tree))
