"""Lightweight span tracing for the host-side driver.

The reference wraps request handling and each schedule in tracing spans
("ggrs"/"HandleRequests", "SaveWorld", "LoadWorld", "AdvanceWorld" —
/root/reference/src/schedule_systems.rs:171,224-253) and relies on the host
engine's tracing backend.  Here ``span`` feeds two sinks: stdlib logging
(always) and the telemetry timeline when enabled (``set_span_sink`` — the
timeline then carries the spans into ``telemetry.chrome_trace()`` as
Perfetto slices).  The JAX profiler covers the device side
(``jax.profiler.trace``).

The module-local ``(name, t0, t1)`` ring this module once kept is gone:
phase attribution moved to :mod:`..telemetry.phases` (exact per-phase
timers with flight-recorder persistence) and span *export* to
:mod:`..telemetry.trace`.  ``get_trace_events`` / ``clear_trace_events``
remain as deprecated no-op shims so old callers keep importing.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Callable, Optional

logger = logging.getLogger("bevy_ggrs_tpu")

_ENABLED = True
_SPAN_SINK: Optional[Callable[[str, float, float], None]] = None


def set_tracing(enabled: bool) -> None:
    """Globally enable/disable span recording."""
    global _ENABLED
    _ENABLED = enabled


def set_span_sink(sink: Optional[Callable[[str, float, float], None]]) -> None:
    """Install a callback fed every completed span as ``(name, t0, t1)``.

    The telemetry timeline (``telemetry.enable()``) installs its sink here;
    None uninstalls.  The sink runs inside the span's ``finally`` — keep it
    cheap and non-raising."""
    global _SPAN_SINK
    _SPAN_SINK = sink


@contextlib.contextmanager
def span(name: str):
    """Context manager recording a named wall-clock span."""
    if not _ENABLED:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        if _SPAN_SINK is not None:
            _SPAN_SINK(name, t0, t1)
        logger.debug("span %s: %.3f ms", name, (t1 - t0) * 1e3)


def trace_log(msg: str, *args) -> None:
    """Debug-level log line on the framework logger."""
    logger.debug(msg, *args)


def get_trace_events():
    """Deprecated: the module-local span ring is gone.  Always returns
    ``[]``.  Use ``telemetry.flight_recorder().snapshot("tick")`` for phase
    attribution or ``telemetry.chrome_trace()`` for span export."""
    return []


def clear_trace_events() -> None:
    """Deprecated no-op (see :func:`get_trace_events`)."""
