from .frames import (
    NULL_FRAME,
    I32_MIN,
    I32_MAX,
    wrap_i32,
    frame_add,
    frame_diff,
    frame_lt,
    frame_le,
    frame_gt,
    frame_ge,
    frame_max,
    frame_min,
)
from .tracing import span, trace_log, get_trace_events

__all__ = [
    "NULL_FRAME",
    "I32_MIN",
    "I32_MAX",
    "wrap_i32",
    "frame_add",
    "frame_diff",
    "frame_lt",
    "frame_le",
    "frame_gt",
    "frame_ge",
    "frame_max",
    "frame_min",
    "span",
    "trace_log",
    "get_trace_events",
]
