"""Synchronous host->device commit for persistent staging buffers.

jax transfers host (numpy) arguments asynchronously — both ``device_put``
and jit argument commits return before the copy lands.  A persistent
staging buffer that is rewritten on the next tick can therefore race an
in-flight upload: under scheduler pressure the transfer reads the NEXT
tick's bytes, which surfaces as a bit-stable-but-wrong checksum (the
SyncTest oracle catches it as a mismatch on an early frame, since the
widest window is the first dispatch's compile stall).

``commit`` starts the copy and blocks until the TRANSFER (not any
dependent computation) completes, so the caller may immediately reuse the
host buffer while the dispatch itself stays fully asynchronous.  Every
reused staging buffer — packed or three-upload — must pass through here
before it reaches a jitted program.

``BGT_SANITIZE=1`` arms the :class:`TransferSanitizer`: commits
version-stamp their backing host buffer, rotation landings clear the
stamp, and every rewrite funnel (``pack_prefix``, the census row stagers)
asks permission first — a rewrite of a still-in-flight buffer raises
:class:`TransferRaceError` at the exact racing write instead of
corrupting an upload.  Donated arrays (``jax.jit(...,
donate_argnums=...)`` recycle paths) get the same treatment through
:meth:`TransferSanitizer.donate` / :meth:`~TransferSanitizer.
guard_donated`.  Disabled (the default), every hook is a single
attribute-check no-op off the hot path's critical arithmetic; the
``stage_uploads`` bench arm gates both prices."""

from __future__ import annotations

import os

import jax


class TransferRaceError(RuntimeError):
    """A staging buffer or donated array was reused before its transfer
    landed — the exact silent-corruption race the static BGT063 rule and
    this runtime sanitizer exist to catch."""


class TransferSanitizer:
    """Version-stamp ledger for in-flight host->device transfers.

    The ledger keys on ``id()`` of the *backing* buffer (``_base`` walks
    the numpy ``.base`` chain, so committing ``buf[:k]`` and rewriting
    ``buf`` meet on the same key).  Donated arrays live in a separate
    insertion-ordered table trimmed to the newest ``_DONATED_CAP`` entries
    — a bounded window is the honest contract for an ``id()``-keyed table
    (a freed array's id can be recycled by the allocator; keeping the
    table short keeps the false-alarm window shorter than any real
    recycle cadence, which revisits a key every wave).

    Every public method early-returns on ``self.enabled`` — that single
    boolean check is the entire disabled-path cost, gated under 1.5us per
    packed tick by the ``stage_uploads`` bench arm."""

    _DONATED_CAP = 64

    def __init__(self, enabled=None):
        if enabled is None:
            enabled = os.environ.get("BGT_SANITIZE", "") == "1"
        self.enabled = bool(enabled)
        self.violations = 0
        self._versions = 0
        self._inflight = {}  # id(base) -> (version, note)
        self._donated = {}  # id(arr) -> note, insertion-ordered

    @staticmethod
    def _base(buf):
        while getattr(buf, "base", None) is not None:
            buf = buf.base
        return buf

    def _violate(self, rule, msg):
        self.violations += 1
        from .. import telemetry

        telemetry.count(
            "sanitizer_violations_total",
            help="transfer races caught by the BGT_SANITIZE runtime "
                 "sanitizer, by rule",
            rule=rule,
        )
        raise TransferRaceError(msg)

    def begin(self, buf, note=""):
        """A transfer of ``buf`` is now in flight: stamp its backing."""
        if not self.enabled:
            return
        self._versions += 1
        self._inflight[id(self._base(buf))] = (self._versions, note)

    def land(self, buf):
        """The transfer consuming ``buf`` has landed: clear the stamp."""
        if not self.enabled:
            return
        self._inflight.pop(id(self._base(buf)), None)

    def guard_write(self, buf, site=""):
        """Called by every staging rewrite funnel before touching ``buf``."""
        if not self.enabled:
            return
        entry = self._inflight.get(id(self._base(buf)))
        if entry is not None:
            version, note = entry
            self._violate(
                "staging_reuse",
                f"staging buffer rewrite at {site or '<unknown>'} while "
                f"upload #{version}{f' ({note})' if note else ''} is still "
                "in flight — acquire() the rotation (or block on the "
                "commit) before rewriting",
            )

    def donate(self, arr, note=""):
        """``arr`` was donated to a jitted call: reads now alias freed
        device memory until the owner rebinds it."""
        if not self.enabled or arr is None:
            return
        self._donated[id(arr)] = note
        while len(self._donated) > self._DONATED_CAP:
            self._donated.pop(next(iter(self._donated)))

    def guard_donated(self, arr, site=""):
        """Called before handing ``arr`` back into a dispatch."""
        if not self.enabled or arr is None:
            return
        note = self._donated.get(id(arr))
        if note is not None:
            self._violate(
                "donated_reuse",
                f"donated array reused at {site or '<unknown>'}"
                f"{f' ({note})' if note else ''} — it was consumed by a "
                "donate_argnums dispatch and must be rebound from the "
                "call result",
            )

    def undonate(self, arr):
        """``arr``'s slot was legitimately rebound: forget the donation."""
        if not self.enabled or arr is None:
            return
        self._donated.pop(id(arr), None)

    def reset(self):
        self._inflight.clear()
        self._donated.clear()
        self.violations = 0


_SANITIZER = TransferSanitizer()


def sanitizer() -> TransferSanitizer:
    """The process sanitizer — callers must fetch it per use (not cache
    it) so :func:`set_sanitize` test swaps take effect."""
    return _SANITIZER


def set_sanitize(enabled: bool) -> TransferSanitizer:
    """Swap in a fresh sanitizer (test hook; mirrors BGT_SANITIZE=1)."""
    global _SANITIZER
    _SANITIZER = TransferSanitizer(enabled=enabled)
    return _SANITIZER


def commit(buf, sharding=None):
    """Upload ``buf`` and wait for the copy; returns the device array."""
    from ..telemetry import devmem

    # device-memory accounting: the device copy of the most recent staging
    # commit stays resident until the dispatch consumes it (one dict store
    # — see telemetry/devmem.py's cost posture)
    devmem.note("staging/last_commit", getattr(buf, "nbytes", 0))
    san = _SANITIZER
    san.guard_write(buf, "staging.commit")  # a racing PRIOR upload of buf
    san.begin(buf, "staging.commit")
    x = (
        jax.device_put(buf, sharding)
        if sharding is not None
        else jax.device_put(buf)
    )
    # bgt: ignore[BGT011]: deliberate — blocks on the TRANSFER only, which
    # is what makes persistent staging-buffer reuse safe (module docstring)
    x.block_until_ready()
    san.land(buf)
    return x


class StagingQueue:
    """Device-resident input queue: rotate ``depth`` host staging buffers so
    the transfer-safety block moves off the tick's critical path.

    :func:`commit` above pays one transfer-latency block per upload because a
    SINGLE persistent buffer is rewritten next tick.  With a rotation of
    ``depth >= 2`` buffers the invariant relaxes: buffer i is only rewritten
    ``depth`` acquires later, so its previous upload has had a whole tick (or
    more) of host work to land — :meth:`acquire` blocks only when it has NOT
    (counted in ``deferred_blocks`` vs ``landed_free``), and :meth:`commit`
    starts the copy WITHOUT blocking.  Net effect for the steady 1-frame/
    update P2P cadence: the packed upload of tick N overlaps tick N+1's
    session poll/pack instead of stalling tick N, while the census stays at
    exactly one upload per dispatch."""

    def __init__(self, make_buffer, depth: int = 2):
        if depth < 2:
            raise ValueError("StagingQueue needs depth >= 2 buffers")
        self.buffers = [make_buffer() for _ in range(depth)]
        self._inflight = [None] * depth  # device array of buffer i's last upload
        self._idx = 0
        self.deferred_blocks = 0  # acquires that had to wait on an old upload
        self.landed_free = 0  # acquires whose old upload had already landed

    @property
    def nbytes(self) -> int:
        return sum(getattr(b, "nbytes", 0) for b in self.buffers)

    def acquire(self):
        """Next host buffer in rotation, safe to rewrite: waits for that
        buffer's previous in-flight upload iff it has not landed yet."""
        self._idx = (self._idx + 1) % len(self.buffers)
        old = self._inflight[self._idx]
        if old is not None:
            if _is_ready(old):
                self.landed_free += 1
            else:
                self.deferred_blocks += 1
                # bgt: ignore[BGT011]: deliberate — same transfer-safety
                # block as commit(), but only on the rare tick where the
                # upload from `depth` acquires ago is still in flight
                old.block_until_ready()
            self._inflight[self._idx] = None
        # either branch proved the old upload landed: clear its stamp so
        # the caller's rewrite passes the sanitizer
        _SANITIZER.land(self.buffers[self._idx])
        return self.buffers[self._idx]

    def commit(self, view):
        """Start the upload of ``view`` (a view of the buffer returned by the
        matching :meth:`acquire`) WITHOUT blocking; returns the device array."""
        from ..telemetry import devmem

        devmem.note("staging/last_commit", getattr(view, "nbytes", 0))
        _SANITIZER.begin(view, "StagingQueue.commit")
        # bgt: ignore[BGT063]: rotation protocol — buffer i is rewritten
        # only `depth` acquires later, and acquire() blocks on this very
        # upload iff it has not landed by then (depth >= 2 enforced in
        # __init__); the sanitizer's begin/land stamps enforce the same
        # contract at runtime under BGT_SANITIZE=1
        x = jax.device_put(view)
        self._inflight[self._idx] = x
        return x


def _is_ready(x) -> bool:
    """True when a device array's async transfer/computation has landed."""
    try:
        return bool(x.is_ready())
    except AttributeError:
        return False
