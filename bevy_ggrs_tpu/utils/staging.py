"""Synchronous host->device commit for persistent staging buffers.

jax transfers host (numpy) arguments asynchronously — both ``device_put``
and jit argument commits return before the copy lands.  A persistent
staging buffer that is rewritten on the next tick can therefore race an
in-flight upload: under scheduler pressure the transfer reads the NEXT
tick's bytes, which surfaces as a bit-stable-but-wrong checksum (the
SyncTest oracle catches it as a mismatch on an early frame, since the
widest window is the first dispatch's compile stall).

``commit`` starts the copy and blocks until the TRANSFER (not any
dependent computation) completes, so the caller may immediately reuse the
host buffer while the dispatch itself stays fully asynchronous.  Every
reused staging buffer — packed or three-upload — must pass through here
before it reaches a jitted program."""

from __future__ import annotations

import jax


def commit(buf, sharding=None):
    """Upload ``buf`` and wait for the copy; returns the device array."""
    from ..telemetry import devmem

    # device-memory accounting: the device copy of the most recent staging
    # commit stays resident until the dispatch consumes it (one dict store
    # — see telemetry/devmem.py's cost posture)
    devmem.note("staging/last_commit", getattr(buf, "nbytes", 0))
    x = (
        jax.device_put(buf, sharding)
        if sharding is not None
        else jax.device_put(buf)
    )
    # bgt: ignore[BGT011]: deliberate — blocks on the TRANSFER only, which
    # is what makes persistent staging-buffer reuse safe (module docstring)
    x.block_until_ready()
    return x
