"""Synchronous host->device commit for persistent staging buffers.

jax transfers host (numpy) arguments asynchronously — both ``device_put``
and jit argument commits return before the copy lands.  A persistent
staging buffer that is rewritten on the next tick can therefore race an
in-flight upload: under scheduler pressure the transfer reads the NEXT
tick's bytes, which surfaces as a bit-stable-but-wrong checksum (the
SyncTest oracle catches it as a mismatch on an early frame, since the
widest window is the first dispatch's compile stall).

``commit`` starts the copy and blocks until the TRANSFER (not any
dependent computation) completes, so the caller may immediately reuse the
host buffer while the dispatch itself stays fully asynchronous.  Every
reused staging buffer — packed or three-upload — must pass through here
before it reaches a jitted program."""

from __future__ import annotations

import jax


def commit(buf, sharding=None):
    """Upload ``buf`` and wait for the copy; returns the device array."""
    from ..telemetry import devmem

    # device-memory accounting: the device copy of the most recent staging
    # commit stays resident until the dispatch consumes it (one dict store
    # — see telemetry/devmem.py's cost posture)
    devmem.note("staging/last_commit", getattr(buf, "nbytes", 0))
    x = (
        jax.device_put(buf, sharding)
        if sharding is not None
        else jax.device_put(buf)
    )
    # bgt: ignore[BGT011]: deliberate — blocks on the TRANSFER only, which
    # is what makes persistent staging-buffer reuse safe (module docstring)
    x.block_until_ready()
    return x


class StagingQueue:
    """Device-resident input queue: rotate ``depth`` host staging buffers so
    the transfer-safety block moves off the tick's critical path.

    :func:`commit` above pays one transfer-latency block per upload because a
    SINGLE persistent buffer is rewritten next tick.  With a rotation of
    ``depth >= 2`` buffers the invariant relaxes: buffer i is only rewritten
    ``depth`` acquires later, so its previous upload has had a whole tick (or
    more) of host work to land — :meth:`acquire` blocks only when it has NOT
    (counted in ``deferred_blocks`` vs ``landed_free``), and :meth:`commit`
    starts the copy WITHOUT blocking.  Net effect for the steady 1-frame/
    update P2P cadence: the packed upload of tick N overlaps tick N+1's
    session poll/pack instead of stalling tick N, while the census stays at
    exactly one upload per dispatch."""

    def __init__(self, make_buffer, depth: int = 2):
        if depth < 2:
            raise ValueError("StagingQueue needs depth >= 2 buffers")
        self.buffers = [make_buffer() for _ in range(depth)]
        self._inflight = [None] * depth  # device array of buffer i's last upload
        self._idx = 0
        self.deferred_blocks = 0  # acquires that had to wait on an old upload
        self.landed_free = 0  # acquires whose old upload had already landed

    @property
    def nbytes(self) -> int:
        return sum(getattr(b, "nbytes", 0) for b in self.buffers)

    def acquire(self):
        """Next host buffer in rotation, safe to rewrite: waits for that
        buffer's previous in-flight upload iff it has not landed yet."""
        self._idx = (self._idx + 1) % len(self.buffers)
        old = self._inflight[self._idx]
        if old is not None:
            if _is_ready(old):
                self.landed_free += 1
            else:
                self.deferred_blocks += 1
                # bgt: ignore[BGT011]: deliberate — same transfer-safety
                # block as commit(), but only on the rare tick where the
                # upload from `depth` acquires ago is still in flight
                old.block_until_ready()
            self._inflight[self._idx] = None
        return self.buffers[self._idx]

    def commit(self, view):
        """Start the upload of ``view`` (a view of the buffer returned by the
        matching :meth:`acquire`) WITHOUT blocking; returns the device array."""
        from ..telemetry import devmem

        devmem.note("staging/last_commit", getattr(view, "nbytes", 0))
        x = jax.device_put(view)
        self._inflight[self._idx] = x
        return x


def _is_ready(x) -> bool:
    """True when a device array's async transfer/computation has landed."""
    try:
        return bool(x.is_ready())
    except AttributeError:
        return False
