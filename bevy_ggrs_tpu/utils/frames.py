"""Frame arithmetic with explicit i32 wraparound semantics.

The reference stores frames as ``i32`` and its snapshot ring handles both
wraparound directions explicitly (/root/reference/src/snapshot/mod.rs:159-163,
tests :369-512).  All frame comparisons in this framework go through the
wrapping helpers below so that a session running long enough to wrap i32
keeps working.  ``NULL_FRAME = -1`` matches the ggrs sentinel (the initial
``ConfirmedFrameCount`` is -1, /root/reference/src/snapshot/mod.rs:79-86).
"""

from __future__ import annotations

I32_MIN = -(2**31)
I32_MAX = 2**31 - 1

#: Sentinel for "no frame" (matches ggrs NULL_FRAME; initial confirmed frame).
NULL_FRAME = -1


def wrap_i32(x: int) -> int:
    """Wrap a python int into i32 two's-complement range."""
    return ((x + 2**31) % 2**32) - 2**31


def frame_add(a: int, n: int) -> int:
    """a + n with i32 wraparound."""
    return wrap_i32(a + n)


def frame_diff(a: int, b: int) -> int:
    """Wrapping signed distance a - b.  Positive => a is newer than b."""
    return wrap_i32(a - b)


def frame_lt(a: int, b: int) -> bool:
    """True if a is older than b under wrapping order."""
    return frame_diff(a, b) < 0


def frame_le(a: int, b: int) -> bool:
    """a <= b under wrapping order."""
    return frame_diff(a, b) <= 0


def frame_gt(a: int, b: int) -> bool:
    """a > b under wrapping order."""
    return frame_diff(a, b) > 0


def frame_ge(a: int, b: int) -> bool:
    """a >= b under wrapping order."""
    return frame_diff(a, b) >= 0


def frame_max(a: int, b: int) -> int:
    """Newer of a, b under wrapping order."""
    return a if frame_ge(a, b) else b


def frame_min(a: int, b: int) -> int:
    """Older of a, b under wrapping order."""
    return a if frame_le(a, b) else b
