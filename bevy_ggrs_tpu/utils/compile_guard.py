"""Steady-state recompile sentinel — the runtime twin of the BGT07x lints.

Every hot-path guarantee the engine ships (1+1 upload/dispatch census,
O(1) speculative servicing, bit-exact migration) silently assumes XLA
programs stay *cached*: one stray recompile is a 10-50ms cliff in the
middle of a 60Hz tick.  The BGT070/BGT071 static rules catch the hazards
a parser can prove (per-call-varying ``static_argnums``, data-dependent
shapes); this module catches the rest at runtime.

Usage mirrors the ``BGT_SANITIZE`` transfer sanitizer:

* ``BGT_COMPILE_GUARD=1`` (or :func:`set_compile_guard`) enables the
  guard process-wide; it starts **disarmed** so warmup compiles pass.
* After warmup, call :meth:`GgrsRunner.arm_compile_guard` /
  :meth:`BatchedRunner.arm_compile_guard` (or :meth:`CompileGuard.arm`
  directly).  From that point ANY program compile observed by the
  engine's compile-accounting sites (``runner._note_compile``, the wave
  executor's first-dispatch timer) increments
  ``recompiles_steady_total{owner}`` and raises :class:`RecompileError`
  naming the owner and program kind — the same sites that already emit
  the ``compile`` flight instant and ``program_compile_ms`` histogram,
  so armed runs add no parallel metric names for warmup compiles.
* ``arm(watch_jax=True)`` additionally registers a
  ``jax.monitoring`` listener so compiles *outside* the hooked sites
  (a stray ``jax.jit`` in user code — exactly what BGT070 flags
  statically) trip the guard too.

Disabled (the default), the whole feature is one module-global load and
attribute check per *compile event* — steady-state ticks never reach the
hook at all, same budget discipline as the transfer sanitizer.
"""

from __future__ import annotations

import os
from typing import List, Tuple

from .. import telemetry

_ENV = "BGT_COMPILE_GUARD"

_HELP = (
    "program compiles observed after the BGT_COMPILE_GUARD sentinel was "
    "armed (steady state; a healthy run stays at 0)"
)


class RecompileError(RuntimeError):
    """A program compiled while the guard was armed (steady state)."""

    def __init__(self, owner: str, kind: str, ms: float = 0.0):
        self.owner = owner
        self.kind = kind
        self.ms = ms
        super().__init__(
            f"steady-state recompile: owner={owner!r} kind={kind!r} "
            f"({ms:.1f}ms) — a post-warmup compile means a cache-key or "
            "shape-stability hazard (see BGT070/BGT071 in "
            "docs/static-analysis.md); every such compile is a frame-time "
            "cliff the tick budget cannot absorb"
        )


class CompileGuard:
    """Post-warmup compile sentinel (module singleton; see :func:`guard`)."""

    __slots__ = ("enabled", "armed", "watch_jax", "steady_compiles")

    def __init__(self, enabled: bool = None):
        if enabled is None:
            enabled = os.environ.get(_ENV, "0") not in ("", "0", "false")
        self.enabled = bool(enabled)
        self.armed = False
        self.watch_jax = False
        # (owner, kind, ms) of every armed-state compile observed —
        # retained even though _trip raises, for post-mortem asserts
        self.steady_compiles: List[Tuple[str, str, float]] = []

    def arm(self, watch_jax: bool = False) -> bool:
        """Declare warmup over.  No-op (returns False) unless the guard
        is enabled, so engine code may call this unconditionally.

        ``watch_jax=True`` also trips on compiles the engine's own
        accounting never sees (raw ``jax.jit`` in user code), via a
        ``jax.monitoring`` backend-compile listener."""
        if not self.enabled:
            return False
        self.armed = True
        if watch_jax:
            self.watch_jax = True
            _install_jax_listener()
        return True

    def disarm(self) -> None:
        self.armed = False
        self.watch_jax = False

    def notify(self, owner: str, kind: str, ms: float = 0.0) -> None:
        """Hook for compile-accounting sites; raises when armed."""
        if self.armed:
            self._trip(owner, str(kind), ms)

    def _trip(self, owner: str, kind: str, ms: float) -> None:
        self.steady_compiles.append((owner, kind, ms))
        telemetry.count("recompiles_steady_total", help=_HELP, owner=owner)
        raise RecompileError(owner, kind, ms)


_GUARD = CompileGuard()

# jax.monitoring listener registration is append-only (no unregister),
# so install at most one process-wide listener that defers to the
# current singleton's armed/watch_jax state.
_JAX_LISTENER_INSTALLED = False


def _install_jax_listener() -> None:
    global _JAX_LISTENER_INSTALLED
    if _JAX_LISTENER_INSTALLED:
        return
    try:
        from jax import monitoring as _mon
    except ImportError:  # pragma: no cover - jax always present in CI
        return

    def _on_event(event: str, duration: float, **kw) -> None:
        g = _GUARD
        if g.armed and g.watch_jax and "backend_compile" in event:
            g._trip("jax", event, duration * 1e3)

    _mon.register_event_duration_secs_listener(_on_event)
    _JAX_LISTENER_INSTALLED = True


def guard() -> CompileGuard:
    """The process-wide guard (the instance engine hooks consult)."""
    return _GUARD


def set_compile_guard(enabled: bool) -> CompileGuard:
    """Swap in a fresh guard (tests/bench): enabled as given, disarmed,
    empty history.  Returns the new singleton."""
    global _GUARD
    _GUARD = CompileGuard(enabled=enabled)
    return _GUARD


def notify(owner: str, kind: str, ms: float = 0.0) -> None:
    """Module-level fast path for engine hooks: one global load plus one
    attribute check when disarmed (<1.5us, benched in stage_uploads)."""
    g = _GUARD
    if g.armed:
        g._trip(owner, str(kind), ms)
